"""Paged decode-serving tests (ISSUE 18): the page allocator + prefix
hash table, paged-vs-flat-vs-oracle token bit-identity (including CoW
divergence and chunked admissions), admission capacity >= 4x the flat
pool at EQUAL KV HBM (census-pinned), heap donation/flatness under the
``kv_pages`` census owner, chunked-prefill scheduling (a 10k-token
admission never stalls generations), page-exhaustion queueing, the
paged program contracts, env catalog, and the threaded engine smoke.
"""
import threading

import numpy as np
import pytest

from mxnet_tpu import programs, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.engine import engine
from mxnet_tpu.serve.decode import (DecodeBatcher, DecodeConfig,
                                    DecodeServable, PagedDecodeBatcher,
                                    PagedDecodeServable,
                                    reference_generate)
from mxnet_tpu.serve.paging import (HASH_SEED, SCRATCH_PAGE,
                                    PageAllocator, chain_hash,
                                    page_hashes)
from mxnet_tpu.telemetry import registry

# the flat suite's geometry + the paged knobs: pages_per_slot = 7,
# kv_pages = 35, 4 programs to warm (3 slot buckets + 1 chunk)
PCFG = dict(dim=16, heads=2, layers=2, slots=4, max_tokens=12,
            prompt_buckets=(4, 8), kv_page_len=4, prefill_chunk=4)


@pytest.fixture(scope="module")
def paged_sv():
    """One warmed paged servable; tests build their own (cheap) sync
    engines on it sequentially — each engine brings a fresh allocator
    and the chunk trains overwrite whatever the last tenant left."""
    cfg = DecodeConfig(**PCFG)
    return PagedDecodeServable(config=cfg), cfg


def _sync_engine(sv, **kw):
    return PagedDecodeBatcher(sv, autostart=False, **kw)


def _ref(sv, cfg, prompt, n):
    return reference_generate(prompt, n, params=sv.params, config=cfg)


# ---------------------------------------------------------------------------
# host-side bookkeeping: prefix hashes + the page allocator
# ---------------------------------------------------------------------------


def test_page_hashes_cover_whole_prefix():
    # hashes[i] covers prompt[:(i+1)*page_len]: equality at page i
    # implies the ENTIRE prefix matches, so chains diverge forever
    # after the first differing page
    a = page_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
    b = page_hashes([1, 2, 3, 4, 9, 6, 7, 8], 4)
    assert len(a) == len(b) == 2
    assert a[0] == b[0] and a[1] != b[1]
    # same last page after different first pages must NOT collide
    c = page_hashes([9, 2, 3, 4, 5, 6, 7, 8], 4)
    assert c[1] != a[1]
    # a trailing partial page is never hashed (not shareable)
    assert len(page_hashes([1, 2, 3, 4, 5], 4)) == 1
    assert len(page_hashes([1, 2, 3], 4)) == 0
    assert chain_hash(HASH_SEED, [1, 2, 3, 4]) == a[0]


def test_allocator_lifecycle():
    al = PageAllocator(6)            # pages 1..5 usable, 0 scratch
    assert al.free_pages() == 5
    held = al.alloc(3)
    assert len(held) == 3 and SCRATCH_PAGE not in held
    assert al.free_pages() == 2
    assert al.alloc(3) is None       # over capacity: NOTHING taken
    assert al.free_pages() == 2
    # publish one page, share it, then release the original holder:
    # the extra ref keeps it live, ref 0 parks it in the LRU cache
    assert al.publish(77, held[0])
    assert not al.publish(77, held[1])      # first writer wins
    assert al.lookup(77) == held[0]
    assert al.shared_extra_refs() == 1
    al.release(held[0])
    assert al.shared_extra_refs() == 0
    al.release(held[0])              # ref 0 -> cached, still adoptable
    assert al.free_pages() == 3 and al.stats()["cached"] == 1
    assert al.lookup(77) == held[0]  # adopted straight from the cache
    al.release(held[0])
    # exhaust the free list: the cached page is evicted (hash gone)
    rest = al.alloc(3)
    assert rest is not None and al.evictions == 1
    assert al.lookup(77) is None
    # double release is a bookkeeping bug, not a silent no-op
    al2 = PageAllocator(4)
    (p,) = al2.alloc(1)
    al2.release(p)
    with pytest.raises(MXNetError):
        al2.release(p)
    with pytest.raises(MXNetError):
        PageAllocator(1)


# ---------------------------------------------------------------------------
# token bit-identity: paged == flat == oracle
# ---------------------------------------------------------------------------


def test_paged_matches_flat_and_oracle(paged_sv):
    """The tentpole's correctness bar: greedy decode through the page
    heap (chunked prefill included) is TOKEN-IDENTICAL to the flat
    engine and the full-recompute oracle."""
    sv, cfg = paged_sv
    flat = DecodeBatcher(DecodeServable(config=DecodeConfig(
        **{k: v for k, v in PCFG.items()
           if k not in ("kv_page_len", "prefill_chunk")})),
        autostart=False)
    prompts = [[3, 1, 4, 1], [5, 9, 2, 6, 5, 3], [2, 7, 1, 8, 2, 8, 1, 8],
               [1, 2], [9, 9, 9, 9, 9, 1, 1]]
    for max_new in (1, 4, 8):
        eng = _sync_engine(sv)
        gens = [eng.submit(p, max_new=max_new) for p in prompts]
        fgens = [flat.submit(p, max_new=max_new) for p in prompts]
        eng.drain_sync()
        flat.drain_sync()
        for p, g, f in zip(prompts, gens, fgens):
            ref = _ref(sv, cfg, p, max_new)
            assert g.tokens_so_far() == ref, (p, max_new)
            assert f.tokens_so_far() == ref, (p, max_new)


def test_chunked_admission_identical(paged_sv):
    """An 8-token prompt admits as TWO 4-token chunks; chunk grouping
    must be bitwise-invisible (each prefill row attends independently
    over the same pages)."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    c0 = registry.value("serve.decode.prefill_chunks")
    p = [7, 3, 2, 9, 4, 4, 1, 6]
    g = eng.submit(p, max_new=6)
    eng.drain_sync()
    assert g.tokens_so_far() == _ref(sv, cfg, p, 6)
    assert registry.value("serve.decode.prefill_chunks") - c0 == 2


def test_cow_and_partial_share_match_oracle(paged_sv):
    """Prefix reuse must be invisible to tokens: a full-coverage hit
    forks CoW and replays ONE position; a partial hit prefills only
    the divergent suffix — both still exactly match the oracle."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    donor = [2, 7, 1, 8, 2, 8, 1, 8]           # 2 full pages, published
    g0 = eng.submit(donor, max_new=4)
    eng.drain_sync()
    assert g0.tokens_so_far() == _ref(sv, cfg, donor, 4)
    c0 = registry.value("serve.decode.prefill_chunks")
    cow0 = registry.value("serve.decode.cow_forks")
    sh0 = registry.value("serve.decode.shared_page_hits")
    # full coverage -> CoW: ONE replay chunk instead of two
    g1 = eng.submit(donor, max_new=6)
    eng.drain_sync()
    assert g1.tokens_so_far() == _ref(sv, cfg, donor, 6)
    assert registry.value("serve.decode.prefill_chunks") - c0 == 1
    assert registry.value("serve.decode.cow_forks") - cow0 == 1
    # shared first page + divergent suffix -> one suffix chunk
    c1 = registry.value("serve.decode.prefill_chunks")
    fork = donor[:4] + [5, 5, 3, 1]
    g2 = eng.submit(fork, max_new=6)
    eng.drain_sync()
    assert g2.tokens_so_far() == _ref(sv, cfg, fork, 6)
    assert registry.value("serve.decode.prefill_chunks") - c1 == 1
    assert registry.value("serve.decode.shared_page_hits") - sh0 >= 2
    # and the donor pages were never corrupted by either adopter
    g3 = eng.submit(donor, max_new=6)
    eng.drain_sync()
    assert g3.tokens_so_far() == g1.tokens_so_far()


def test_shared_pages_survive_donor_retire(paged_sv):
    """Published pages park in the allocator's LRU at ref 0 — a LATER
    session still adopts them (the cross-request prefix cache), and
    correctness holds after the reuse."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    donor = [6, 1, 6, 1, 3, 8, 3, 8]
    eng.submit(donor, max_new=2)
    eng.drain_sync()                 # donor done + retired
    st = eng.page_stats()
    assert st["kv_cached_pages"] >= 2
    c0 = registry.value("serve.decode.prefill_chunks")
    g = eng.submit(donor, max_new=5)
    eng.drain_sync()
    assert g.tokens_so_far() == _ref(sv, cfg, donor, 5)
    assert registry.value("serve.decode.prefill_chunks") - c0 == 1


# ---------------------------------------------------------------------------
# the tentpole's capacity claim: >= 4x concurrency at EQUAL KV HBM
# ---------------------------------------------------------------------------


def test_admission_capacity_4x_at_equal_hbm():
    """Flat pool, slots=2: 2 concurrent sessions, period.  The paged
    heap with the SAME pool bytes (census-pinned) runs the mixed
    workload 6x as wide, because short sessions hold 1 page instead of
    a worst-case flat extent."""
    base = dict(dim=8, heads=1, layers=1, max_tokens=16,
                prompt_buckets=(4, 64))
    flat_sv = DecodeServable(config=DecodeConfig(slots=2, **base))
    paged_cfg = DecodeConfig(slots=12, kv_page_len=16, kv_pages=18,
                             **base)
    paged_sv = PagedDecodeServable(config=paged_cfg)
    # EQUAL KV HBM: flat (slots+1) x max_len extents == 18 pages x 16
    flat_pool = 2 * 1 * 3 * flat_sv.config.max_len * 8 * 4
    paged_pool = paged_sv.page_bytes() * paged_cfg.kv_pages
    assert flat_pool == paged_pool == 18432
    census = programs.buffer_census()
    assert census["kv_cache"]["bytes"] >= flat_pool
    assert census["kv_pages"]["bytes"] >= paged_pool
    eng = PagedDecodeBatcher(paged_sv, autostart=False)
    long_p = list(np.arange(64) % 7 + 1)
    gens = [eng.submit(long_p, max_new=16)]
    gens += [eng.submit([1 + i % 5, 2, 3, 4], max_new=2)
             for i in range(11)]
    eng.step_sync()                  # admission is one boundary
    got = eng.active_count()
    assert got == 12 >= 4 * flat_sv.config.slots
    eng.drain_sync()
    for g, p, n in zip(gens, [long_p] + [[1 + i % 5, 2, 3, 4]
                                         for i in range(11)],
                       [16] + [2] * 11):
        assert g.tokens_so_far() == reference_generate(
            p, n, params=paged_sv.params, config=paged_cfg)


def test_page_exhaustion_queues_then_admits():
    """When the heap is full the head-of-line request WAITS (bounded by
    pages, not slots) and admits — correctly — once a retire frees
    pages.  Nothing is half-allocated meanwhile."""
    cfg = DecodeConfig(dim=8, heads=1, layers=1, slots=12,
                       max_tokens=16, prompt_buckets=(4, 64),
                       kv_page_len=16, kv_pages=18)
    sv = PagedDecodeServable(config=cfg)
    eng = PagedDecodeBatcher(sv, autostart=False)
    long_p = list(np.arange(64) % 7 + 1)
    eng.submit(long_p, max_new=16)               # 6 pages
    shorts = [eng.submit([2, 2, 2, 2], max_new=2)
              for _ in range(11)]                # 11 x 1 page = 17 total
    eng.step_sync()
    assert eng.active_count() == 12              # heap full
    extra = eng.submit([3, 3, 3, 3], max_new=2)
    eng.step_sync()
    assert not extra.done() and eng.queue_depth() == 1
    assert eng.page_stats()["kv_free_pages"] == 0
    eng.drain_sync()                             # retire frees pages
    assert extra.done()
    assert extra.tokens_so_far() == reference_generate(
        [3, 3, 3, 3], 2, params=sv.params, config=cfg)
    assert all(g.done() for g in shorts)


# ---------------------------------------------------------------------------
# chunked prefill scheduling: long admissions never stall the pump
# ---------------------------------------------------------------------------


def test_10k_prefill_interleaves_with_decode():
    """A 10240-token admission is a 20-chunk train; chunk dispatches
    ROUND-ROBIN with other sessions' chunks and ALTERNATE with decode
    steps, so short generations admitted alongside finish while the
    long prefill is still in flight — and the long result is invariant
    to the chunk size (the chunked-prefill correctness proof that
    avoids a 10k-position monolithic oracle)."""
    base = dict(dim=8, heads=1, layers=1, slots=4, max_tokens=8,
                prompt_buckets=(32, 10240))
    rs = np.random.RandomState(3)
    long_p = list(rs.randint(1, 40, size=10240))
    short_p = list(rs.randint(1, 40, size=32))

    def run(chunk):
        cfg = DecodeConfig(kv_page_len=64, prefill_chunk=chunk, **base)
        eng = PagedDecodeBatcher(PagedDecodeServable(config=cfg),
                                 autostart=False)
        lg = eng.submit(long_p, max_new=4)
        sg = [eng.submit(short_p, max_new=2) for _ in range(2)]
        ticks_until_shorts = None
        for t in range(1, 9):
            eng.step_sync()
            if ticks_until_shorts is None and all(g.done() for g in sg):
                ticks_until_shorts = t
        # shorts done within 8 ticks; the 20-chunk train is NOT
        assert ticks_until_shorts is not None
        assert not lg.done()
        eng.drain_sync(max_ticks=200)
        short_ref = reference_generate(short_p, 2,
                                       params=eng._sv.params,
                                       config=cfg)
        for g in sg:
            assert g.tokens_so_far() == short_ref
        return lg.tokens_so_far(), short_ref

    out_512 = run(512)
    out_1024 = run(1024)
    assert out_512 == out_1024       # chunk size never changes tokens


# ---------------------------------------------------------------------------
# budgets: dispatches, retraces, heap flatness, donation, contracts
# ---------------------------------------------------------------------------


def test_paged_dispatch_budget_and_zero_retraces(paged_sv):
    """Every device dispatch is either one prefill chunk or one decode
    step — nothing else — and the warmed program table answers all of
    them (zero serve-time retraces)."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    retr0 = sv.retraces
    c0 = engine.snapshot()["dispatches"]
    ch0 = registry.value("serve.decode.prefill_chunks")
    st0 = registry.value("serve.decode.steps")
    pre0 = registry.value("serve.decode.prefills")
    gens = [eng.submit([2, 4, 6], max_new=5) for _ in range(4)]
    eng.drain_sync()
    dispatches = engine.snapshot()["dispatches"] - c0
    chunks = registry.value("serve.decode.prefill_chunks") - ch0
    steps = registry.value("serve.decode.steps") - st0
    assert chunks == 4               # one-page prompts: 1 chunk each
    assert registry.value("serve.decode.prefills") - pre0 == 4
    assert dispatches == chunks + steps
    assert sv.retraces == retr0
    assert all(len(g.tokens_so_far()) == 5 for g in gens)


def test_heap_flat_census_owner_and_donation(paged_sv):
    """The page heap is allocated ONCE: 200 generations later the
    ``kv_pages`` census bytes are unchanged, and every dispatch donated
    the previous heap buffers (no double-residency)."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    census0 = programs.buffer_census()
    assert "kv_pages" in census0
    assert census0["kv_pages"]["bytes"] >= sv.kv_state_bytes()
    b0 = sv.kv_state_bytes()
    old = dict(sv._state)
    done = 0
    while done < 200:
        gens = [eng.submit([3, 1 + done % 5], max_new=3)
                for _ in range(4)]
        eng.drain_sync()
        done += len(gens)
    assert sv.kv_state_bytes() == b0
    after = programs.buffer_census()["kv_pages"]["bytes"]
    assert after == census0["kv_pages"]["bytes"]
    assert sv._state["k"] is not old["k"]
    assert old["k"].is_deleted()     # donated into the first dispatch
    assert old["len"].is_deleted()


def test_dispatch_count_paged_budget():
    """The CLI harness (tools/dispatch_count.py --serve --decode) pins
    the same arithmetic: chunks are counted as steps, at most one
    dispatch per pump tick, zero retraces."""
    import tools.dispatch_count as dc
    report = dc.run_paged_decode(n_gens=4, prompt_len=8, max_new=4,
                                 slots=4)
    assert report["ok"], report
    assert report["max_dispatches_per_tick"] <= 1
    assert report["prefill_chunk_dispatches"] == 8
    assert report["dispatches"] == (report["prefill_chunk_dispatches"]
                                    + report["decode_steps"])


def test_paged_contracts_declared():
    names = {c.name for c in programs.contracts()}
    assert "serve.paged.decode" in names
    assert "serve.paged.prefill" in names
    by_name = {c.name: c for c in programs.contracts()}
    assert by_name["serve.paged.decode"].donate_argnums == (1, 2, 3, 4)
    assert by_name["serve.paged.prefill"].donate_argnums == (1, 2, 3, 4)


def test_paged_env_catalog():
    from mxnet_tpu.base import ENV_CATALOG
    for name in ("MX_SERVE_KV_PAGES", "MX_SERVE_KV_PAGE_LEN",
                 "MX_SERVE_PREFIX_SHARE", "MX_SERVE_PREFILL_CHUNK"):
        assert name in ENV_CATALOG, name
        default, doc = ENV_CATALOG[name]
        assert default is not None and doc


def test_paged_engine_surface(paged_sv):
    """The health/fleet projection: engine discriminator, page stats,
    and the headroom gauges the router and fleet_top consume."""
    sv, cfg = paged_sv
    eng = _sync_engine(sv)
    assert sv.engine == "paged" and sv.census_owner == "kv_pages"
    st = eng.page_stats()
    assert st["engine"] == "paged"
    assert st["kv_pages"] == cfg.kv_pages
    assert st["prefill_chunk"] == cfg.prefill_chunk
    eng.submit([5, 5], max_new=2)
    eng.drain_sync()
    assert registry.find("serve.decode.kv_free_pages") is not None
    assert registry.value("serve.decode.kv_free_pages") \
        == eng.page_stats()["kv_free_pages"]
    # the flat engine must NOT grow page stats
    assert super(PagedDecodeBatcher, eng).page_stats() is None
    with pytest.raises(MXNetError):
        PagedDecodeBatcher(sv, mode="request", autostart=False)
    with pytest.raises(MXNetError):
        sv.prefill_program(8)
    with pytest.raises(MXNetError):
        sv.dispatch_prefill(0, np.zeros(4, np.int32), 2)


def test_threaded_paged_smoke(paged_sv):
    """The real (pump + harvester) threads over the paged engine: a
    burst of mixed + shared-prefix generations all complete correctly
    and the engine closes clean."""
    sv, cfg = paged_sv
    eng = PagedDecodeBatcher(sv)
    try:
        prompts = [[5, 6, 7], [2, 2], [9, 1, 3, 8], [9, 1, 3, 8]]
        news = (8, 2, 5, 5)
        refs = [_ref(sv, cfg, p, n) for p, n in zip(prompts, news)]
        gens = [eng.submit(p, max_new=n)
                for p, n in zip(prompts, news)]
        gens += [eng.submit(prompts[0], max_new=8) for _ in range(5)]
        outs = [g.result(timeout=60) for g in gens]
        assert outs[:4] == refs
        assert all(o == refs[0] for o in outs[4:])
    finally:
        eng.close()
    eng.close()
    assert not eng._pump.is_alive() and not eng._harvester.is_alive()
