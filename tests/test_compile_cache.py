"""Persistent compiled-program cache + async input pipeline (ISSUE 13).

Covers the tentpole's safety contract — every cache-poisoning/skew path
is non-fatal and counted, warm loads are bit-identical — and the
prefetcher's parity/lifecycle guarantees:

* executable store roundtrip: second Program deserializes, no compile,
  identical outputs; donation aliasing survives deserialization
* corrupt entry / truncated write / envelope skew / unpicklable
  payload: counted miss (+error), normal compile, correct answers
* concurrent writers: last-write-wins via atomic rename, no torn reads
* key hygiene: function edits and jit-spec changes change the key;
  repeated runs of one process produce the identical key (no memory
  addresses, no set-ordering leakage)
* CompiledStep / Servable warm-from-cache continue the exact cold
  trajectory
* DevicePrefetcher: bit-parity loss trajectory, bounded queue, error
  transparency, clean shutdown, data_wait telemetry
* mxlint reinjection: a host sync in the prefetch handoff and disk I/O
  in the batcher loop both trip host-sync-in-hot-path
"""
import json
import os
import pickle
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                                                   # noqa: E402
import jax.numpy as jnp                                      # noqa: E402

import mxnet_tpu as mx                                       # noqa: E402
from mxnet_tpu import compile_cache as cc                    # noqa: E402
from mxnet_tpu import gluon, nd, programs, telemetry         # noqa: E402
from mxnet_tpu.base import environment                       # noqa: E402
from mxnet_tpu.io.prefetch import DevicePrefetcher           # noqa: E402

_uid = [0]


def _name(tag):
    _uid[0] += 1
    return "test.cc.%s.%d.%d" % (tag, os.getpid(), _uid[0])


def _cache_env(tmp_path):
    d = str(tmp_path / "xcache")
    os.makedirs(d, exist_ok=True)
    return environment("MX_COMPILE_CACHE", d)


def _stats_delta(fn):
    before = cc.stats()
    out = fn()
    after = cc.stats()
    delta = {k: after[k] - before[k]
             for k in ("hits", "misses", "errors", "writes")}
    return out, delta


# ---------------------------------------------------------------------------
# store roundtrip
# ---------------------------------------------------------------------------

def test_roundtrip_second_program_deserializes(tmp_path):
    def fn(x, y):
        return x @ y + 1.0

    a = jnp.ones((4, 8), jnp.float32)
    b = jnp.ones((8, 2), jnp.float32)
    name = _name("roundtrip")
    with _cache_env(tmp_path):
        p1 = programs.register_program(name, fn)
        out1, d1 = _stats_delta(lambda: p1(a, b))
        assert d1["writes"] == 1 and d1["hits"] == 0
        rec = programs.find_record(name)
        assert rec.compiles == 1 and rec.cache_hits == 0

        # a FRESH wrapper (new process stand-in): loads, never compiles
        name2 = _name("roundtrip2")
        p2 = programs.Program(name2, "aot", fn, {}, aot=True)
        # same fn/sig/jit_kw → same key as p1's entry
        assert cc.cache_key(name, programs.signature_of((a, b)), fn=fn,
                            jit_kw={}) == \
            cc.cache_key(name, programs.signature_of((a, b)), fn=fn,
                         jit_kw={})
        out2, d2 = _stats_delta(lambda: p1_clone_dispatch(p2, a, b))
        assert d2["hits"] == 0  # different name → different key: compiles
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def p1_clone_dispatch(p, a, b):
    return p(a, b)


def test_same_name_fresh_wrapper_hits_and_matches(tmp_path):
    def fn(x):
        return jnp.tanh(x) * 3.0

    x = jnp.linspace(-2, 2, 32).reshape(4, 8)
    name = _name("hit")
    with _cache_env(tmp_path):
        p1 = programs.register_program(name, fn)
        out1 = p1(x)
        rec1 = programs.find_record(name)
        assert rec1.compiles == 1

        p2 = programs.Program(name + ".warm", "aot", fn, {}, aot=True)
        # force the same on-disk key by construction: identical
        # name is what real warm restarts share — emulate by pointing
        # the fresh wrapper at the original name
        p2._name = name
        out2, delta = _stats_delta(lambda: p2(x))
        assert delta["hits"] == 1
        assert delta["writes"] == 0
        rec = programs.find_record(name)
        assert rec.cache_hits == 1
        # deserialize time tracked separately; no compile charged
        assert rec.compiles == 1
        assert rec.snapshot()["deserialize_seconds"] > 0
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_donation_survives_deserialization(tmp_path):
    def fn(x, y):
        return x + y

    name = _name("donate")
    with _cache_env(tmp_path):
        p1 = programs.register_program(name, fn, donate_argnums=(0,))
        x = jnp.ones((16,), jnp.float32)
        p1(x, x + 1)

        p2 = programs.Program(name, "aot", fn, {"donate_argnums": (0,)},
                              aot=True)
        # same aval as the cold call (jnp.full would flip weak_type and
        # honestly be a different trace)
        xd = jnp.ones((16,), jnp.float32) * 5.0
        out, delta = _stats_delta(lambda: p2(xd, xd + 1))
        assert delta["hits"] == 1
        jax.block_until_ready(out)
        assert xd.is_deleted()      # the aliasing rode the serialization
        np.testing.assert_array_equal(np.asarray(out), np.full(16, 11.0))


def test_cache_off_writes_nothing(tmp_path):
    # MX_COMPILE_CACHE unset: register_program is cacheless — no files,
    # no counters moving
    with environment("MX_COMPILE_CACHE", None):
        assert not cc.enabled()
        before = cc.stats()
        p = programs.register_program(_name("off"), lambda x: x * 2)
        p(jnp.ones((3,)))
        after = cc.stats()
    assert after["hits"] == before["hits"]
    assert after["misses"] == before["misses"]
    assert after["writes"] == before["writes"]
    assert not (tmp_path / "xcache").exists() or \
        not list((tmp_path / "xcache").rglob("*.xcache"))


# ---------------------------------------------------------------------------
# poisoning / skew: all non-fatal, all counted
# ---------------------------------------------------------------------------

def _single_entry(tmp_path):
    entries = [p for p in (tmp_path / "xcache").rglob("*.xcache")]
    assert len(entries) == 1, entries
    return entries[0]


def test_corrupt_entry_falls_back_and_counts(tmp_path):
    def fn(x):
        return x - 7.0

    name = _name("corrupt")
    x = jnp.ones((8,), jnp.float32)
    with _cache_env(tmp_path):
        programs.register_program(name, fn)(x)
        entry = _single_entry(tmp_path)
        entry.write_bytes(b"\x00garbage not a pickle")
        p2 = programs.Program(name, "aot", fn, {}, aot=True)
        out, delta = _stats_delta(lambda: p2(x))
        assert delta["hits"] == 0
        assert delta["misses"] == 1 and delta["errors"] == 1
        np.testing.assert_array_equal(np.asarray(out), np.full(8, -6.0))
        # the poisoned entry was removed and the recompile re-published
        assert _single_entry(tmp_path).read_bytes()[:1] != b"\x00"


def test_truncated_write_falls_back(tmp_path):
    def fn(x):
        return x * x

    name = _name("trunc")
    x = jnp.full((4,), 3.0)
    with _cache_env(tmp_path):
        programs.register_program(name, fn)(x)
        entry = _single_entry(tmp_path)
        blob = entry.read_bytes()
        entry.write_bytes(blob[:len(blob) // 3])    # torn tail
        p2 = programs.Program(name, "aot", fn, {}, aot=True)
        out, delta = _stats_delta(lambda: p2(x))
        assert delta["misses"] == 1 and delta["errors"] == 1
        np.testing.assert_array_equal(np.asarray(out), np.full(4, 9.0))


def test_envelope_skew_is_a_miss_not_a_wrong_load(tmp_path):
    def fn(x):
        return x + 100.0

    name = _name("skew")
    x = jnp.zeros((4,))
    with _cache_env(tmp_path):
        programs.register_program(name, fn)(x)
        entry = _single_entry(tmp_path)
        doc = pickle.loads(entry.read_bytes())
        doc["envelope"] = dict(doc["envelope"], jax="0.0.1-other")
        entry.write_bytes(pickle.dumps(doc))
        p2 = programs.Program(name, "aot", fn, {}, aot=True)
        out, delta = _stats_delta(lambda: p2(x))
        assert delta["hits"] == 0
        assert delta["misses"] == 1
        np.testing.assert_array_equal(np.asarray(out), np.full(4, 100.0))


def test_unserializable_out_tree_counts_error_keeps_working(tmp_path):
    # the hybridize-train class of program: a function rides the out
    # tree (jax.tree_util.Partial with a local closure) — store() must
    # count an error and the program must keep dispatching
    def fn(x):
        def local_fn(y):
            return y * x.sum()
        return x * 2, jax.tree_util.Partial(local_fn, x)

    name = _name("unser")
    x = jnp.ones((4,))
    with _cache_env(tmp_path):
        p = programs.register_program(name, fn)
        _, delta = _stats_delta(lambda: p(x))
        assert delta["writes"] == 0
        assert delta["errors"] >= 1     # serialize failed, counted
        rec = programs.find_record(name)
        assert rec is not None          # ...and the dispatch succeeded


def test_concurrent_writers_last_write_wins_no_torn_reads(tmp_path):
    d = str(tmp_path / "xcache")
    os.makedirs(d, exist_ok=True)

    def fn(x):
        return x * 4.0

    x = jnp.ones((64,), jnp.float32)
    name = _name("race")
    with environment("MX_COMPILE_CACHE", d):
        sig = programs.signature_of((x,))
        key = cc.cache_key(name, sig, fn=fn, jit_kw={})
        compiled = jax.jit(fn).lower(x).compile()
        errs = []

        def writer():
            try:
                for _ in range(10):
                    assert cc.store(name, key, compiled)
            except Exception as e:      # pragma: no cover
                errs.append(e)

        def reader():
            try:
                for _ in range(30):
                    got = cc.load(name, key)
                    if got is not None:
                        np.testing.assert_array_equal(
                            np.asarray(got(x)), np.full(64, 4.0))
            except Exception as e:      # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer) for _ in range(3)] + \
                  [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        # exactly one published entry; any .tmp droppings are stale
        entries = [p for p in (tmp_path / "xcache").rglob("*.xcache")]
        assert len(entries) == 1
        got = cc.load(name, key)
        assert got is not None


# ---------------------------------------------------------------------------
# key hygiene
# ---------------------------------------------------------------------------

def test_function_edit_changes_key():
    def fn_a(x):
        return x + 1

    def fn_b(x):
        return x + 2

    sig = programs.signature_of((jnp.ones((3,)),))
    assert cc.function_fingerprint(fn_a) != cc.function_fingerprint(fn_b)
    with environment("MX_COMPILE_CACHE", "/tmp/x"):
        assert cc.cache_key("p", sig, fn=fn_a) != \
            cc.cache_key("p", sig, fn=fn_b)


def test_jit_spec_changes_key():
    def fn(x):
        return x + 1

    sig = programs.signature_of((jnp.ones((3,)),))
    with environment("MX_COMPILE_CACHE", "/tmp/x"):
        assert cc.cache_key("p", sig, fn=fn, jit_kw={}) != \
            cc.cache_key("p", sig, fn=fn,
                         jit_kw={"donate_argnums": (0,)})


def test_closure_and_default_values_change_key():
    # trace bodies bake closed-over host config (weight decays, flags)
    # into the executable invisibly to the trace signature — the
    # fingerprint MUST see them or a warm restart deserializes the
    # other config's program
    def make(c):
        def fn(x):
            return x * c
        return fn

    assert cc.function_fingerprint(make(2.0)) != \
        cc.function_fingerprint(make(3.0))
    assert cc.function_fingerprint(make(2.0)) == \
        cc.function_fingerprint(make(2.0))

    def fd_a(x, k=2):
        return x + k

    def fd_b(x, k=3):
        return x + k

    fd_b.__name__ = "fd_a"      # identical but for the default
    assert cc.function_fingerprint(fd_a) != cc.function_fingerprint(fd_b)

    # nested: the divergent value sits one closure level down
    def outer(c):
        def mid(x):
            def inner(y):
                return y * c
            return inner(x)
        return mid

    assert cc.function_fingerprint(outer(1)) != \
        cc.function_fingerprint(outer(2))


def test_compiled_step_wd_change_changes_key():
    # end-to-end: two CompiledStep bodies with identical shapes but
    # different weight decay must never share a cache entry
    from mxnet_tpu.gluon import nn

    def build(wd):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=8, activation="relu"))
        net.add(nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9,
                            "wd": wd})
        cs = tr.make_compiled_step(net,
                                   gluon.loss.SoftmaxCrossEntropyLoss())
        plan = cs._plan()
        rescale, wds, _lr, _d = cs._lr_rows(plan, 1, 8)
        return cs._build_fn(plan, 1, 1, rescale, wds, False, None,
                            False)._fn

    assert cc.function_fingerprint(build(0.0)) != \
        cc.function_fingerprint(build(0.01))


def test_partial_and_frozenset_fingerprints_are_stable():
    import functools

    def body(x, mode):
        if mode in {"a", "b", "c"}:
            return x + 1
        return x

    f1 = functools.partial(body, mode="a")
    f2 = functools.partial(body, mode="a")
    f3 = functools.partial(body, mode="b")
    assert cc.function_fingerprint(f1) == cc.function_fingerprint(f2)
    assert "0x" not in cc._stable_repr(f1)
    assert cc.function_fingerprint(f1) != cc.function_fingerprint(f3)


def test_salt_partitions_the_key():
    def fn(x):
        return x

    sig = programs.signature_of((jnp.ones((2,)),))
    with environment("MX_COMPILE_CACHE", "/tmp/x"):
        k1 = cc.cache_key("p", sig, fn=fn)
        with environment("MX_COMPILE_CACHE_SALT", "exp-7"):
            k2 = cc.cache_key("p", sig, fn=fn)
    assert k1 != k2


def test_signature_token_distinguishes_shape_dtype_sharding():
    a = programs.signature_of((jnp.ones((4, 2), jnp.float32),))
    b = programs.signature_of((jnp.ones((4, 3), jnp.float32),))
    c = programs.signature_of((jnp.ones((4, 2), jnp.bfloat16),))
    toks = {cc.signature_token(s) for s in (a, b, c)}
    assert len(toks) == 3


# ---------------------------------------------------------------------------
# warm-start consumers
# ---------------------------------------------------------------------------

def _mlp_trainer(seed=0):
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _run_cstep(steps=4):
    net, tr = _mlp_trainer()
    cstep = tr.make_compiled_step(net,
                                  gluon.loss.SoftmaxCrossEntropyLoss())
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 8).astype(np.float32))
    y = nd.array(rng.randint(0, 4, 8).astype(np.float32))
    losses = []
    for _ in range(steps):
        loss = cstep.step(x, y)
        losses.append(float(loss.mean().asnumpy()))
    return losses


def test_compiled_step_warm_from_cache_exact_trajectory(tmp_path):
    with _cache_env(tmp_path):
        cold = _run_cstep()
        w0 = cc.stats()
        warm = _run_cstep()     # fresh CompiledStep → fresh Program →
        #                         disk load instead of compile
        delta_hits = cc.stats()["hits"] - w0["hits"]
    assert delta_hits >= 1
    assert warm == cold         # bit-identical trajectory


def test_servable_warm_from_cache_skips_compiles(tmp_path):
    from mxnet_tpu.serve.demo import demo_block, demo_example
    from mxnet_tpu.serve.servable import BucketTable, Servable
    buckets = BucketTable([1, 2, 4])
    with _cache_env(tmp_path):
        sv1 = Servable(demo_block(), name=_name("sv"), version=1,
                       buckets=buckets)
        sv1.warm(demo_example())
        w0 = cc.stats()
        assert w0["writes"] >= 3

        sv2 = Servable(demo_block(), name=sv1.name, version=2,
                       buckets=buckets)
        sv2.warm(demo_example())
        w1 = cc.stats()
        assert w1["hits"] - w0["hits"] == 3
        # warm answers == cold answers
        x = np.random.RandomState(3).randn(2, 16).astype(np.float32)
        pad = np.zeros((2, 16), np.float32)
        o1 = sv1.dispatch(2, [x])
        o2 = sv2.dispatch(2, [x])
        np.testing.assert_array_equal(np.asarray(o1[0]),
                                      np.asarray(o2[0]))
        assert pad is not None


# ---------------------------------------------------------------------------
# census / telemetry wiring
# ---------------------------------------------------------------------------

def test_cache_hit_census_columns_and_summary(tmp_path):
    def fn(x):
        return x * 2 + 1

    name = _name("census")
    x = jnp.ones((4,))
    with _cache_env(tmp_path):
        programs.register_program(name, fn)(x)
        p2 = programs.Program(name, "aot", fn, {}, aot=True)
        p2(x)
        snap = programs.find_record(name).snapshot()
        assert snap["cache_hits"] == 1
        assert snap["deserialize_seconds"] > 0
        summary = programs.program_summary()
        assert summary["cache_hits"] >= 1
        assert "deserialize_seconds_total" in summary
        st = cc.stats()
        assert st["enabled"] and st["hits"] >= 1
        # counters ride the registry exposition (fleet rollup merges
        # registry snapshots generically, so presence here == presence
        # in the merged fleet face)
        reg_snap = telemetry.registry.snapshot()
        assert any(e.get("name") == "compile_cache.hits"
                   for e in reg_snap.values() if isinstance(e, dict))
        prom = telemetry.registry.to_prometheus()
        assert "mx_compile_cache_hits" in prom


def test_specializing_record_semantics():
    name = _name("spec")
    p = programs.register_program(name, lambda x: x + 1, mode="light",
                                  specializing=True)
    p(jnp.ones((2,)))
    p(jnp.ones((3,)))           # fresh shape: specialization, NOT retrace
    rec = programs.find_record(name)
    assert rec.compiles == 2
    assert rec.retraces == 0
    assert rec.specializations == 1
    snap = rec.snapshot()
    assert snap["specializing"] and snap["specializations"] == 1


def test_strict_record_semantics_unchanged():
    name = _name("strict")
    p = programs.register_program(name, lambda x: x + 1, mode="light")
    p(jnp.ones((2,)))
    p(jnp.ones((3,)))
    rec = programs.find_record(name)
    assert rec.retraces == 1 and rec.specializations == 0


def test_hybridize_imperative_pass_builds_no_child_programs():
    # ISSUE 13 retrace chase: the deferred-init imperative pass of a
    # hybridized parent must not build per-child hybrid programs —
    # the whole-net trace on the SECOND call covers them
    from mxnet_tpu.gluon import nn
    before = set(programs.program_table())
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"))     # deferred in_units
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(np.random.RandomState(0).randn(2, 6).astype(np.float32))
    net(x)                      # imperative pass (finishes deferred init)
    new = set(programs.program_table()) - before
    assert not any(n.startswith("hybrid.Dense") for n in new), new
    net(x)                      # whole-net trace
    new = set(programs.program_table()) - before
    assert any(n.startswith("hybrid.HybridSequential") for n in new), new


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------

def _mlp_loss_traj(use_prefetch, steps=6):
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(7)
    batches = [(rng.randn(8, 8).astype(np.float32),
                rng.randn(8, 4).astype(np.float32))
               for _ in range(steps)]
    from mxnet_tpu import autograd

    def one(xb, yb):
        with autograd.record():
            loss = loss_fn(net(xb), yb)
        loss.backward()
        tr.step(batch_size=8)
        return float(loss.mean().asnumpy())

    if use_prefetch:
        with DevicePrefetcher(iter(batches)) as pf:
            return [one(nd.NDArray(xb), nd.NDArray(yb)) for xb, yb in pf]
    return [one(nd.array(xb), nd.array(yb)) for xb, yb in batches]


def test_prefetch_bit_parity_loss_trajectory():
    assert _mlp_loss_traj(False) == _mlp_loss_traj(True)


def test_prefetch_bounded_queue_and_order():
    produced = []

    def src():
        for i in range(50):
            produced.append(i)
            yield (np.full((2,), i, np.float32),)

    pf = DevicePrefetcher(src(), depth=2)
    first = next(pf)
    time.sleep(0.3)
    assert len(produced) <= 5           # depth + in-flight margin
    assert float(first[0][0]) == 0.0
    out = [float(b[0][0]) for b in pf]
    assert out == [float(i) for i in range(1, 50)]
    pf.close()


def test_prefetch_error_surfaces_on_consumer():
    def bad():
        yield (np.zeros((1,)),)
        raise RuntimeError("disk on fire")

    pf = DevicePrefetcher(bad())
    next(pf)
    with pytest.raises(mx.base.MXNetError, match="disk on fire"):
        next(pf)
    pf.close()


def test_prefetch_close_idempotent_and_bounded():
    def src():
        while True:
            yield (np.zeros((1,)),)

    pf = DevicePrefetcher(src(), depth=1)
    next(pf)
    t0 = time.monotonic()
    pf.close()
    pf.close()
    assert time.monotonic() - t0 < 5
    with pytest.raises(mx.base.MXNetError):
        next(pf)


def test_prefetch_data_wait_phase_observed():
    inst0 = telemetry.registry.find("step_phase_seconds",
                                    {"phase": "data_wait"})
    c0 = inst0.snapshot()["count"] if inst0 is not None else 0
    with environment("MX_TELEMETRY", "1"):
        with DevicePrefetcher([(np.zeros((1,)),)] * 3) as pf:
            for _ in pf:
                pass
    inst = telemetry.registry.find("step_phase_seconds",
                                   {"phase": "data_wait"})
    assert inst is not None
    assert inst.snapshot()["count"] >= c0 + 3


def test_prefetch_ndarray_leaves_roundtrip():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    with DevicePrefetcher([(x,)]) as pf:
        (out,) = next(pf)
    assert isinstance(out, nd.NDArray)
    np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())


def test_prefetch_depth_env(tmp_path):
    with environment("MX_PREFETCH_DEPTH", "5"):
        from mxnet_tpu.io.prefetch import prefetch_depth
        assert prefetch_depth() == 5
    with environment("MX_PREFETCH_DEPTH", "0"):
        assert __import__(
            "mxnet_tpu.io.prefetch", fromlist=["prefetch_depth"]
        ).prefetch_depth() == 1


# ---------------------------------------------------------------------------
# env catalog + mxlint reinjection
# ---------------------------------------------------------------------------

def test_new_env_vars_cataloged():
    from mxnet_tpu.base import ENV_CATALOG
    for var in ("MX_COMPILE_CACHE", "MX_COMPILE_CACHE_SALT",
                "MX_PREFETCH", "MX_PREFETCH_DEPTH"):
        assert var in ENV_CATALOG, var


def _lint_source(code, path):
    from tools.mxlint import lint_source
    return lint_source(code, path)


def _rules_of(diags):
    return {d.rule for d in diags}


def test_reinjected_sync_in_prefetch_handoff_trips():
    p = os.path.join(REPO, "mxnet_tpu", "io", "prefetch.py")
    with open(p) as f:
        code = f.read()
    anchor = "_telemetry.observe_phase(\"data_wait\", " \
             "self._clock() - t0)"
    assert anchor in code, "prefetch handoff moved; update this test"
    bad = code.replace(
        anchor, anchor + "\n        _dbg = item[0].asnumpy()")
    diags = _lint_source(bad, "mxnet_tpu/io/prefetch.py")
    assert "host-sync-in-hot-path" in _rules_of(diags)


def test_reinjected_disk_io_in_batcher_loop_trips():
    # the satellite's contract verbatim: no disk I/O inside the batcher
    # loop — an open() reintroduced between dequeue and dispatch trips
    # host-sync-in-hot-path
    p = os.path.join(REPO, "mxnet_tpu", "serve", "batcher.py")
    with open(p) as f:
        code = f.read()
    anchor = "batch = self._collect()"
    assert anchor in code, "Batcher._loop moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n            open('/tmp/spill', 'a').write('x')")
    diags = _lint_source(bad, "mxnet_tpu/serve/batcher.py")
    assert "host-sync-in-hot-path" in _rules_of(diags)


# ---------------------------------------------------------------------------
# bench_compare gated series (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def _bc():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_compare_cc_test",
        os.path.join(REPO, "tools", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _hist_rows(rows):
    return [(i + 1, r) for i, r in enumerate(rows)]


def test_bench_compare_gates_retrace_budget():
    bc = _bc()
    rec = {"metric": "m", "device": "cpu", "host": "h", "unit": "x",
           "value": 10.0, "retraces": 9, "retrace_budget": 4,
           "retraces_over_budget": True}
    ok, findings = bc.gate(rec, _hist_rows([dict(rec, value=10.0,
                                                 retraces_over_budget=False)]),
                           0.10, 0.15)
    assert not ok
    assert any("RETRACE BUDGET" in f for f in findings)


def test_bench_compare_gates_compile_seconds_per_warmth_class():
    bc = _bc()
    base = {"metric": "m", "device": "cpu", "host": "h", "unit": "x",
            "value": 10.0}
    history = _hist_rows([
        dict(base, compile_seconds_total=20.0, cache_hits=0),   # cold best
        dict(base, compile_seconds_total=0.5, cache_hits=7),    # warm best
    ])
    # a cold run near the cold best passes — the warm 0.5s is NOT its bar
    ok, _ = bc.gate(dict(base, compile_seconds_total=21.0, cache_hits=0),
                    history, 0.10, 0.15)
    assert ok
    # a cold run regressing >10% vs the cold best fails
    ok, findings = bc.gate(dict(base, compile_seconds_total=25.0,
                                cache_hits=0), history, 0.10, 0.15)
    assert not ok and any("COMPILE-TIME" in f for f in findings)
    # a warm run regressing vs the warm best fails
    ok, findings = bc.gate(dict(base, compile_seconds_total=2.0,
                                cache_hits=7), history, 0.10, 0.15)
    assert not ok and any("warm" in f for f in findings)


def test_bench_compare_gates_warm_spawn_seconds():
    bc = _bc()
    base = {"metric": "serve_warm_spawn_speedup", "device": "cpu",
            "host": "h", "unit": "x", "value": 8.0}
    history = _hist_rows([dict(base, warm_spawn_seconds=3.5)])
    ok, _ = bc.gate(dict(base, warm_spawn_seconds=3.6), history,
                    0.10, 0.15)
    assert ok
    ok, findings = bc.gate(dict(base, warm_spawn_seconds=5.0), history,
                           0.10, 0.15)
    assert not ok and any("WARM-SPAWN" in f for f in findings)


def test_bench_compare_extracts_issue13_fields():
    bc = _bc()
    report = {
        "metric": "m", "value": 1.0, "unit": "x", "device": "cpu",
        "retrace_budget": 4, "retraces_over_budget": False,
        "warm_spawn_seconds": 3.5, "cold_spawn_seconds": 28.0,
        "prefetch": {"data_wait_share_pct": 0.2},
        "census": {"summary": {"compile_seconds_total": 1.2,
                               "peak_temp_bytes": 10, "retraces": 0,
                               "programs": 5, "cache_hits": 7}},
    }
    rec = bc.extract_record(report)
    assert rec["retrace_budget"] == 4
    assert rec["warm_spawn_seconds"] == 3.5
    assert rec["cache_hits"] == 7
    assert rec["data_wait_share_pct"] == 0.2


def test_reinjected_open_in_compile_cache_key_trips():
    p = os.path.join(REPO, "mxnet_tpu", "compile_cache.py")
    with open(p) as f:
        code = f.read()
    anchor = "h.update(signature_token(sig).encode())"
    assert code.count(anchor) == 1, "cache_key moved; update this test"
    bad = code.replace(
        anchor,
        "with open('/tmp/keylog', 'a') as _f:\n"
        "        _f.write(name)\n    " + anchor)
    diags = _lint_source(bad, "mxnet_tpu/compile_cache.py")
    assert "host-sync-in-hot-path" in _rules_of(diags)
