"""mx.contrib.text (reference pattern:
tests/python/unittest/test_contrib_text.py — counters, vocabulary
indexing invariants, embedding loading from token files, composite
embeddings, registry/catalog)."""
import os
from collections import Counter

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import text


def _counter():
    return text.utils.count_tokens_from_str(
        "life is great ! \n life is good . \n")


def test_count_tokens_from_str():
    c = _counter()
    assert c == Counter({"life": 2, "is": 2, "great": 1, "!": 1,
                         "good": 1, ".": 1})
    c2 = text.utils.count_tokens_from_str(
        "Life is GREAT\nlife is good", to_lower=True)
    assert c2["life"] == 2 and c2["great"] == 1
    # in-place update of an existing counter
    base = Counter({"life": 10})
    out = text.utils.count_tokens_from_str("life is",
                                           counter_to_update=base)
    assert out is base and base["life"] == 11 and base["is"] == 1


def test_vocabulary_indexing_invariants():
    v = text.vocab.Vocabulary(_counter(), most_freq_count=None,
                              min_freq=1, unknown_token="<unk>",
                              reserved_tokens=["<pad>"])
    # index 0 unk, then reserved, then by descending freq (alpha ties)
    assert v.idx_to_token[0] == "<unk>"
    assert v.idx_to_token[1] == "<pad>"
    assert set(v.idx_to_token[2:4]) == {"is", "life"}
    assert len(v) == 8
    assert v.to_indices("unseen-token") == 0
    assert v.to_indices(["life", "unseen"]) == [v.token_to_idx["life"], 0]
    assert v.to_tokens(0) == "<unk>"
    assert v.to_tokens([0, 1]) == ["<unk>", "<pad>"]
    with pytest.raises(ValueError):
        v.to_tokens(len(v))


def test_vocabulary_most_freq_and_min_freq():
    v = text.vocab.Vocabulary(_counter(), most_freq_count=2, min_freq=1)
    assert len(v) == 3            # unk + 2 most frequent
    v2 = text.vocab.Vocabulary(_counter(), min_freq=2)
    assert set(v2.idx_to_token[1:]) == {"life", "is"}
    with pytest.raises(AssertionError):
        text.vocab.Vocabulary(_counter(), min_freq=0)
    with pytest.raises(AssertionError):
        text.vocab.Vocabulary(_counter(), reserved_tokens=["<unk>"])


def _write_custom(path, lines):
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return str(path)


def test_custom_embedding_loads_and_queries(tmp_path):
    p = _write_custom(tmp_path / "emb.txt", [
        "a 0.1 0.2 0.3",
        "b 1.0 2.0 3.0",
        "c -1.0 -2.0 -3.0",
    ])
    e = text.embedding.CustomEmbedding(p)
    assert e.vec_len == 3
    assert len(e) == 4            # unk + 3 tokens
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("b").asnumpy(), [1.0, 2.0, 3.0], rtol=1e-6)
    # unknown -> init_unknown_vec (zeros by default)
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("zzz").asnumpy(), [0, 0, 0], atol=0)
    two = e.get_vecs_by_tokens(["a", "c"]).asnumpy()
    np.testing.assert_allclose(two[1], [-1, -2, -3], rtol=1e-6)
    # lower_case_backup
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("B", lower_case_backup=True).asnumpy(),
        [1.0, 2.0, 3.0], rtol=1e-6)


def test_custom_embedding_malformed_lines_and_unk_row(tmp_path):
    p = _write_custom(tmp_path / "emb.txt", [
        "a 0.1 0.2",
        "broken 0.1 xyz",          # unparsable -> warn + skip
        "dup 1.0 1.0",
        "dup 9.9 9.9",             # duplicate -> first wins
        "<unk> 7.0 8.0",           # explicit unknown vector row
        "short 0.5",               # dim mismatch -> skip
    ])
    e = text.embedding.CustomEmbedding(p)
    assert "broken" not in e.token_to_idx
    assert "short" not in e.token_to_idx
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("dup").asnumpy(), [1.0, 1.0], rtol=1e-6)
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("never-seen").asnumpy(), [7.0, 8.0],
        rtol=1e-6)


def test_custom_embedding_with_vocabulary(tmp_path):
    p = _write_custom(tmp_path / "emb.txt", [
        "life 1 1", "is 2 2", "great 3 3"])
    v = text.vocab.Vocabulary(_counter(), most_freq_count=3)
    e = text.embedding.CustomEmbedding(p, vocabulary=v)
    # vocabulary drives the index space, embedding supplies vectors
    assert len(e) == len(v)
    assert e.idx_to_token == v.idx_to_token
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("life").asnumpy(), [1, 1], rtol=1e-6)
    # vocab token absent from the embedding file -> unk vector (zeros)
    missing = [t for t in v.idx_to_token[1:]
               if t not in ("life", "is", "great")]
    if missing:
        np.testing.assert_allclose(
            e.get_vecs_by_tokens(missing[0]).asnumpy(), [0, 0], atol=0)


def test_update_token_vectors(tmp_path):
    p = _write_custom(tmp_path / "emb.txt", ["a 1 1", "b 2 2"])
    e = text.embedding.CustomEmbedding(p)
    e.update_token_vectors("a", nd.array([9.0, 9.0]))
    np.testing.assert_allclose(
        e.get_vecs_by_tokens("a").asnumpy(), [9, 9], rtol=1e-6)
    e.update_token_vectors(["a", "b"], nd.array([[1., 2.], [3., 4.]]))
    np.testing.assert_allclose(e.idx_to_vec.asnumpy()[1:],
                               [[1, 2], [3, 4]], rtol=1e-6)
    with pytest.raises(ValueError):
        e.update_token_vectors("nope", nd.array([0.0, 0.0]))


def test_composite_embedding_concatenates(tmp_path):
    p1 = _write_custom(tmp_path / "e1.txt", ["x 1 2", "y 3 4"])
    p2 = _write_custom(tmp_path / "e2.txt", ["x 5 7", "z 6 8"])
    e1 = text.embedding.CustomEmbedding(p1)
    e2 = text.embedding.CustomEmbedding(p2)
    v = text.vocab.Vocabulary(Counter({"x": 2, "y": 1, "z": 1}))
    ce = text.embedding.CompositeEmbedding(v, [e1, e2])
    assert ce.vec_len == 4
    np.testing.assert_allclose(
        ce.get_vecs_by_tokens("x").asnumpy(), [1, 2, 5, 7], rtol=1e-6)
    np.testing.assert_allclose(
        ce.get_vecs_by_tokens("y").asnumpy(), [3, 4, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(
        ce.get_vecs_by_tokens("z").asnumpy(), [0, 0, 6, 8], rtol=1e-6)


def test_glove_fasttext_local_root_and_catalog(tmp_path):
    # catalog / registry surface
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names
    assert "glove.6B.50d.txt" in \
        text.embedding.get_pretrained_file_names("glove")
    with pytest.raises(KeyError):
        text.embedding.get_pretrained_file_names("nope")
    with pytest.raises(KeyError):
        text.embedding.create("nope")

    # GloVe from a local drop directory (offline activation path)
    root = tmp_path / "embeddings"
    os.makedirs(root / "glove")
    _write_custom(root / "glove" / "glove.6B.50d.txt",
                  ["hello " + " ".join(["0.5"] * 50),
                   "world " + " ".join(["0.25"] * 50)])
    g = text.embedding.create("glove",
                              pretrained_file_name="glove.6B.50d.txt",
                              embedding_root=str(root))
    assert g.vec_len == 50
    np.testing.assert_allclose(
        g.get_vecs_by_tokens("hello").asnumpy()[:2], [0.5, 0.5])

    # FastText .vec header line is skipped
    os.makedirs(root / "fasttext")
    _write_custom(root / "fasttext" / "wiki.simple.vec",
                  ["2 3", "alpha 1 2 3", "beta 4 5 6"])
    ft = text.embedding.create("fasttext",
                               pretrained_file_name="wiki.simple.vec",
                               embedding_root=str(root))
    assert ft.vec_len == 3 and "alpha" in ft.token_to_idx
    # missing file -> clear offline error, not a download attempt
    with pytest.raises(OSError, match="offline"):
        text.embedding.GloVe(pretrained_file_name="glove.6B.100d.txt",
                             embedding_root=str(root))
    # unknown catalog name -> KeyError
    with pytest.raises(KeyError):
        text.embedding.GloVe(pretrained_file_name="not-a-file.txt",
                             embedding_root=str(root))


def test_embedding_feeds_gluon_embedding_layer(tmp_path):
    """The reference workflow: load vectors, set them into a
    gluon.nn.Embedding weight, look tokens up through the layer."""
    p = _write_custom(tmp_path / "emb.txt",
                      ["cat 1 0", "dog 0 1", "fish 1 1"])
    v = text.vocab.Vocabulary(Counter({"cat": 3, "dog": 2, "fish": 1}))
    e = text.embedding.CustomEmbedding(p, vocabulary=v)
    layer = mx.gluon.nn.Embedding(len(e), e.vec_len)
    layer.initialize()
    layer.weight.set_data(e.idx_to_vec)
    idx = nd.array(e.to_indices(["dog", "cat"]))
    out = layer(idx).asnumpy()
    np.testing.assert_allclose(out, [[0, 1], [1, 0]], rtol=1e-6)
