// Native JPEG decoder — the hot half of the reference's C++ image
// pipeline (src/io/iter_image_recordio_2.cc ImageRecordIOParser2 +
// image_aug_default.cc decode via cv::imdecode).
//
// The GIL-free decode is what lets host CPUs keep a TPU fed: python
// callers (mx.image.imdecode, io.ImageRecordIter workers) drop into this
// via ctypes, so N decode threads scale on N cores instead of fighting
// over the interpreter.  Plain libjpeg (present in the image); extern "C"
// ABI consumed by ctypes — no pybind11 in this environment.
#include <csetjmp>
#include <cstdint>
#include <cstdio>   // jpeglib.h needs FILE declared first
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  // libjpeg's default handler calls exit(); longjmp back out instead
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(mgr->jump, 1);
}

void silent_output(j_common_ptr) {
  // corrupt inputs are a return code, not stderr noise
}

}  // namespace

extern "C" {

// Decode JPEG bytes.  channels_want: 0 = keep source, 1 = grayscale,
// 3 = RGB.  On success returns 0 and *out (malloc'd HWC uint8, caller
// frees with MXImdecodeFree) + dims.  Non-JPEG or corrupt data: -1.
int MXImdecode(const unsigned char* data, uint64_t len, int channels_want,
               unsigned char** out, int* height, int* width,
               int* channels) {
  if (len < 2 || data[0] != 0xFF || data[1] != 0xD8) {
    return -1;  // not a JPEG (PNG etc. stay on the python/PIL path)
  }
  jpeg_decompress_struct cinfo;
  ErrorMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = error_exit;
  err.pub.output_message = silent_output;
  // volatile: modified between setjmp and longjmp — without it the
  // recovery free() may see an indeterminate register value (C99 7.13.2.1)
  unsigned char* volatile buf = nullptr;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(buf);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  if (channels_want == 1) {
    cinfo.out_color_space = JCS_GRAYSCALE;
  } else if (channels_want == 3) {
    cinfo.out_color_space = JCS_RGB;
  }
  jpeg_start_decompress(&cinfo);
  const int h = static_cast<int>(cinfo.output_height);
  const int w = static_cast<int>(cinfo.output_width);
  const int c = static_cast<int>(cinfo.output_components);
  const size_t stride = static_cast<size_t>(w) * c;
  buf = static_cast<unsigned char*>(std::malloc(stride * h));
  if (buf == nullptr) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = buf + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out = buf;
  *height = h;
  *width = w;
  *channels = c;
  return 0;
}

void MXImdecodeFree(unsigned char* buf) { std::free(buf); }

}  // extern "C"
