// Native RecordIO core.
//
// Reference behavior: 3rdparty/dmlc-core/include/dmlc/recordio.h
// (RecordIOWriter/RecordIOReader) and src/recordio.cc — MXNet's on-disk
// .rec container: every record is
//
//   uint32 kMagic = 0xced7230a
//   uint32 lrec   = (cflag << 29) | length          (cflag: 0=whole,
//                   1=first chunk, 2=middle, 3=last — multi-chunk records
//                   appear when payloads embed the magic)
//   byte   data[length], zero-padded to a 4-byte boundary
//
// This implementation is byte-compatible with files produced by the
// reference's im2rec (same magic, same lrec encoding, same padding) and is
// exposed to Python through a minimal C ABI (ctypes — no pybind11 in this
// image).  The reader hands out a pointer into an internally managed
// buffer, valid until the next call on the same handle; the writer returns
// the byte offset of each record so the .idx sidecar can be built the way
// MXIndexedRecordIO expects.
//
// TPU relevance: file parsing is pure host-side runtime — the one place
// where native code pays off is keeping the input pipeline off the Python
// interpreter's critical path while the chip is busy (SURVEY.md hard part:
// sustaining the JPEG/decode rate behind a saturated MXU).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

inline uint32_t EncodeLRec(uint32_t cflag, uint32_t length) {
  return (cflag << 29u) | (length & ((1u << 29u) - 1u));
}
inline uint32_t DecodeFlag(uint32_t rec) { return (rec >> 29u) & 7u; }
inline uint32_t DecodeLength(uint32_t rec) { return rec & ((1u << 29u) - 1u); }

struct Writer {
  FILE* fp = nullptr;
};

struct Reader {
  FILE* fp = nullptr;
  std::vector<char> buf;    // assembled record payload
  std::vector<char> chunk;  // scratch for one chunk
};

// Find the next occurrence of the magic pattern in [begin, end).
const char* FindMagic(const char* begin, const char* end) {
  uint32_t magic = kMagic;
  const char* pat = reinterpret_cast<const char*>(&magic);
  if (end - begin < 4) return nullptr;
  for (const char* p = begin; p + 4 <= end; ++p) {
    if (memcmp(p, pat, 4) == 0) return p;
  }
  return nullptr;
}

}  // namespace

extern "C" {

// ---- writer ---------------------------------------------------------------

void* MXRecordIOWriterCreate(const char* path) {
  FILE* fp = fopen(path, "wb");
  if (!fp) return nullptr;
  Writer* w = new Writer();
  w->fp = fp;
  return w;
}

// Append one record; returns the byte offset of its header (for .idx),
// or -1 on error.  Splits the payload on embedded magic patterns into
// chunks exactly like dmlc::RecordIOWriter::WriteRecord, so readers that
// resynchronize on magic can recover.
int64_t MXRecordIOWriterWrite(void* handle, const char* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w || !w->fp) return -1;
  int64_t pos = static_cast<int64_t>(ftell(w->fp));

  // collect chunk boundaries at embedded magics
  std::vector<std::pair<const char*, uint64_t>> chunks;
  const char* p = data;
  const char* end = data + len;
  while (true) {
    const char* hit = len ? FindMagic(p, end) : nullptr;
    if (hit == nullptr) {
      chunks.emplace_back(p, static_cast<uint64_t>(end - p));
      break;
    }
    chunks.emplace_back(p, static_cast<uint64_t>(hit - p));
    p = hit + 4;  // the magic bytes themselves are elided; flag says "join"
  }

  uint32_t magic = kMagic;
  for (size_t i = 0; i < chunks.size(); ++i) {
    uint32_t cflag;
    if (chunks.size() == 1) {
      cflag = 0;
    } else if (i == 0) {
      cflag = 1;
    } else if (i + 1 == chunks.size()) {
      cflag = 3;
    } else {
      cflag = 2;
    }
    uint32_t clen = static_cast<uint32_t>(chunks[i].second);
    uint32_t lrec = EncodeLRec(cflag, clen);
    if (fwrite(&magic, 4, 1, w->fp) != 1) return -1;
    if (fwrite(&lrec, 4, 1, w->fp) != 1) return -1;
    if (clen && fwrite(chunks[i].first, 1, clen, w->fp) != clen) return -1;
    uint32_t pad = (4 - (clen & 3u)) & 3u;
    uint32_t zero = 0;
    if (pad && fwrite(&zero, 1, pad, w->fp) != pad) return -1;
  }
  return pos;
}

int64_t MXRecordIOWriterTell(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  return w && w->fp ? static_cast<int64_t>(ftell(w->fp)) : -1;
}

void MXRecordIOWriterClose(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (w) {
    if (w->fp) fclose(w->fp);
    delete w;
  }
}

// ---- reader ---------------------------------------------------------------

void* MXRecordIOReaderCreate(const char* path) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return nullptr;
  Reader* r = new Reader();
  r->fp = fp;
  return r;
}

// Read the next logical record (reassembling multi-chunk ones).
// Returns 0 on success (out_data/out_len set, pointer valid until the next
// call), 1 on clean EOF, -1 on corruption/IO error.
int MXRecordIOReaderNext(void* handle, const char** out_data,
                         uint64_t* out_len) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || !r->fp) return -1;
  r->buf.clear();
  bool in_multi = false;
  while (true) {
    uint32_t magic = 0, lrec = 0;
    size_t got = fread(&magic, 1, 4, r->fp);
    if (got == 0 && !in_multi) return 1;  // clean EOF
    if (got != 4 || magic != kMagic) return -1;
    if (fread(&lrec, 4, 1, r->fp) != 1) return -1;
    uint32_t cflag = DecodeFlag(lrec);
    uint32_t clen = DecodeLength(lrec);
    size_t base = r->buf.size();
    if (in_multi) {
      // chunks were split at an elided magic: restore it
      uint32_t m = kMagic;
      r->buf.insert(r->buf.end(), reinterpret_cast<char*>(&m),
                    reinterpret_cast<char*>(&m) + 4);
      base = r->buf.size();
    }
    r->buf.resize(base + clen);
    if (clen && fread(r->buf.data() + base, 1, clen, r->fp) != clen)
      return -1;
    uint32_t pad = (4 - (clen & 3u)) & 3u;
    if (pad) {
      char dump[4];
      if (fread(dump, 1, pad, r->fp) != pad) return -1;
    }
    if (cflag == 0 || cflag == 3) break;  // whole record or last chunk
    in_multi = true;
  }
  *out_data = r->buf.data();
  *out_len = r->buf.size();
  return 0;
}

void MXRecordIOReaderSeek(void* handle, int64_t pos) {
  Reader* r = static_cast<Reader*>(handle);
  if (r && r->fp) fseek(r->fp, static_cast<long>(pos), SEEK_SET);
}

int64_t MXRecordIOReaderTell(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  return r && r->fp ? static_cast<int64_t>(ftell(r->fp)) : -1;
}

void MXRecordIOReaderClose(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r) {
    if (r->fp) fclose(r->fp);
    delete r;
  }
}

}  // extern "C"
