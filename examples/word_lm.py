"""LSTM word-level language model on PTB (BASELINE config 3; reference:
example/rnn/word_lm/train.py — the cuDNN-RNN → XLA-scan headline config).

Trains a tied-embedding LSTM LM with truncated BPTT (hidden state carried
across batches and DETACHED — the reference's `hidden = detach(hidden)`
pattern) and reports per-epoch perplexity + words/sec (Speedometer-style
logging that tools/parse_log.py scrapes).

Real data when ``MX_DATA_DIR/ptb/ptb.train.txt`` exists; otherwise a
synthetic Zipf-distributed corpus keeps the script runnable offline:

    python examples/word_lm.py [--epochs 1] [--bptt 35] [--batch-size 20]
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402


def load_corpus(vocab_size):
    """token-id stream: PTB if dropped at MX_DATA_DIR, else synthetic."""
    data_dir = os.environ.get("MX_DATA_DIR")
    path = data_dir and os.path.join(data_dir, "ptb", "ptb.train.txt")
    if path and os.path.exists(path):
        with open(path) as f:
            words = f.read().replace("\n", " <eos> ").split()
        vocab = {}
        ids = []
        for w in words:
            if w not in vocab and len(vocab) < vocab_size - 1:
                vocab[w] = len(vocab)
            ids.append(vocab.get(w, vocab_size - 1))
        return np.asarray(ids, np.int32), max(len(vocab) + 1, 2)
    # offline: Zipf tokens with Markov structure so the LM has signal
    rng = np.random.RandomState(0)
    n = 40_000
    base = rng.zipf(1.5, n).clip(1, vocab_size - 1)
    ids = np.where(np.arange(n) % 2 == 1,
                   (base * 7 + 3) % vocab_size, base)  # learnable bigram
    return ids.astype(np.int32), vocab_size


def batchify(ids, batch_size):
    nb = len(ids) // batch_size
    return ids[:nb * batch_size].reshape(batch_size, nb).T  # (T, N)


class RNNModel(gluon.HybridBlock):
    """Embedding → LSTM → tied-weight decoder (reference word_lm model)."""

    def __init__(self, vocab_size, embed_size, hidden_size, layers,
                 dropout):
        super().__init__()
        self.embedding = nn.Embedding(vocab_size, embed_size)
        self.lstm = rnn.LSTM(hidden_size, num_layers=layers,
                             dropout=dropout, input_size=embed_size)
        self.drop = nn.Dropout(dropout)
        self.proj = nn.Dense(embed_size, in_units=hidden_size,
                             flatten=False)
        self.vocab_size = vocab_size

    def forward(self, x, state):
        emb = self.drop(self.embedding(x))          # (T, N, E)
        out, state = self.lstm(emb, state)
        out = self.proj(self.drop(out))             # (T, N, E)
        # tied decoder: logits = out @ embedding.weightᵀ
        w = self.embedding.weight.data(out.context)
        logits = nd.invoke("dot", out.reshape((-1, w.shape[1])), w,
                           transpose_b=True)
        return logits.reshape((x.shape[0], x.shape[1], -1)), state


def detach(state):
    return [s.detach() for s in state] if isinstance(state, (list, tuple)) \
        else state.detach()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=20)
    p.add_argument("--bptt", type=int, default=35)
    p.add_argument("--vocab", type=int, default=2000)
    p.add_argument("--embed", type=int, default=64)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=1)
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--max-batches", type=int, default=0,
                   help="cap batches/epoch (CI smoke)")
    args = p.parse_args()

    mx.random.seed(0)
    ids, vocab = load_corpus(args.vocab)
    data = batchify(ids, args.batch_size)           # (T_total, N)
    model = RNNModel(vocab, args.embed, args.hidden, args.layers,
                     args.dropout)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr, "clip_gradient": 0.25})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n_batches = (data.shape[0] - 1) // args.bptt
    if args.max_batches:
        n_batches = min(n_batches, args.max_batches)
    for epoch in range(args.epochs):
        state = model.lstm.begin_state(args.batch_size)
        total_nll, total_words = 0.0, 0
        tic = time.time()
        for i in range(n_batches):
            s = i * args.bptt
            x = nd.array(data[s:s + args.bptt])
            y = nd.array(data[s + 1:s + 1 + args.bptt].astype(np.float32))
            state = detach(state)                  # truncated BPTT
            with autograd.record():
                logits, state = model(x, state)
                loss = loss_fn(logits, y)
            loss.backward()
            trainer.step(args.batch_size * args.bptt)
            total_nll += float(loss.mean().asnumpy()) * x.size
            total_words += x.size
        ppl = math.exp(total_nll / total_words)
        wps = total_words / (time.time() - tic)
        print("Epoch[%d] Train-perplexity=%.2f" % (epoch, ppl))
        print("Epoch[%d] Speed: %.1f samples/sec" % (epoch, wps))
    print("final train perplexity %.2f (vocab=%d)" % (ppl, vocab))


if __name__ == "__main__":
    main()
