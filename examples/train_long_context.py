"""Long-context LM training with ring-attention sequence parallelism
(SURVEY §5.7: long-context is first-class; reference has no equivalent —
this is the TPU-native design the rebuild adds on top of MXNet's surface).

A small causal transformer LM trains with its sequence axis SHARDED over
an 'sp' mesh axis: every attention layer runs mxnet_tpu.parallel.
ring_attention (K/V blocks rotate around the ring via ppermute, flash
kernel per hop), so activation memory per chip scales with L/sp while
the math stays EXACTLY the single-device attention (the parity suite
pins this).  dp × sp composes on one mesh.

    python examples/train_long_context.py [--seq-len 512] [--sp 2]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4, help="global batch")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--sp", type=int, default=0,
                    help="sequence-parallel degree (0 = all devices)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh, ring_attention

    devs = jax.devices()
    sp = args.sp or len(devs)
    assert args.seq_len % sp == 0, "seq-len must divide by sp"
    mesh = make_mesh(axes=("dp", "sp"), shape=(-1, sp), devices=devs)
    print("mesh:", dict(mesh.shape), "| L=%d (L/sp=%d per chip)"
          % (args.seq_len, args.seq_len // sp))

    D, H, V, L = args.d_model, args.heads, args.vocab, args.seq_len
    Dh = D // H
    rng = np.random.RandomState(0)

    def init_params():
        def g(*shape, s=0.02):
            return jnp.asarray(rng.randn(*shape) * s, jnp.float32)
        layers = []
        for _ in range(args.layers):
            layers.append({
                "wqkv": g(D, 3 * D), "wo": g(D, D),
                "w1": g(D, 4 * D), "w2": g(4 * D, D),
                "ln1": jnp.ones(D), "ln2": jnp.ones(D),
            })
        return {"emb": g(V, D), "layers": layers, "lnf": jnp.ones(D)}

    # ring attention over the sp axis: each shard holds L/sp of the
    # sequence; K/V rotate sp hops (causal masking handled per hop).
    # Batch is ALSO sharded (dp) — the ring's scan carry legitimately
    # varies over dp, so relax shard_map's varying-axis check where the
    # jax version enforces it.
    try:
        attn = shard_map(
            partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
            out_specs=P("dp", "sp"), check_vma=False)
    except TypeError:   # older jax: flag named check_rep
        attn = shard_map(
            partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P("dp", "sp"), P("dp", "sp"), P("dp", "sp")),
            out_specs=P("dp", "sp"), check_rep=False)

    def ln(x, gamma):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return gamma * (x - mu) / jnp.sqrt(var + 1e-5)

    def forward(params, tokens):
        B = tokens.shape[0]
        x = params["emb"][tokens]                       # (B, L, D)
        for lyr in params["layers"]:
            h = ln(x, lyr["ln1"])
            qkv = (h @ lyr["wqkv"]).reshape(B, L, 3, H, Dh)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            o = attn(q, k, v).reshape(B, L, D)
            x = x + o @ lyr["wo"]
            h = ln(x, lyr["ln2"])
            x = x + jax.nn.gelu(h @ lyr["w1"]) @ lyr["w2"]
        return ln(x, params["lnf"]) @ params["emb"].T   # tied head

    def loss_fn(params, tokens, targets):
        logits = forward(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        take = jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return -take.mean()

    @jax.jit
    def step(params, opt_m, opt_v, t, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        # Adam, functional (the parallel path stays one jitted step)
        b1, b2, eps = 0.9, 0.999, 1e-8
        opt_m = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, opt_m, grads)
        opt_v = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, opt_v, grads)
        tt = t + 1
        params = jax.tree_util.tree_map(
            lambda p, m, v: p - args.lr * (m / (1 - b1 ** tt))
            / (jnp.sqrt(v / (1 - b2 ** tt)) + eps),
            params, opt_m, opt_v)
        return params, opt_m, opt_v, tt, loss

    # structured synthetic corpus: next token is a deterministic map of
    # the current one, so the LM has signal to model
    perm = rng.permutation(V)

    def batch():
        starts = rng.randint(0, V, args.batch)
        seq = np.zeros((args.batch, L + 1), np.int32)
        seq[:, 0] = starts
        for t in range(1, L + 1):
            seq[:, t] = perm[seq[:, t - 1]]
        return seq[:, :-1], seq[:, 1:]

    params = init_params()
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    opt_m, opt_v, t = zeros, jax.tree_util.tree_map(jnp.zeros_like,
                                                    params), 0
    shard = NamedSharding(mesh, P("dp", "sp"))
    losses = []
    for i in range(args.steps):
        x_np, y_np = batch()
        x = jax.device_put(jnp.asarray(x_np), shard)
        y = jax.device_put(jnp.asarray(y_np), shard)
        params, opt_m, opt_v, t, loss = step(params, opt_m, opt_v, t, x, y)
        losses.append(float(loss))
        if i % 10 == 0 or i == args.steps - 1:
            print("step %3d  loss %.4f" % (i, losses[-1]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    print("final loss %.4f (from %.4f) over L=%d with sp=%d"
          % (losses[-1], losses[0], L, sp))


if __name__ == "__main__":
    main()
