"""Classic bucketed LSTM language model (reference:
example/rnn/bucketing/lstm_bucketing.py).

The full pre-Gluon stack end to end: mx.rnn.BucketSentenceIter bins
variable-length sentences into buckets, a sym_gen builds one unrolled
graph per bucket with mx.rnn symbolic cells (weights shared across
buckets through the names), and BucketingModule.fit switches compiled
executables per batch.  Offline it runs on synthetic sentences; point
MX_DATA_DIR at a PTB-style corpus (one sentence per line of ints) to
arm it.

    python examples/lstm_bucketing.py [--num-epochs 2] [--num-layers 2]
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def load_sentences(vocab):
    data_dir = os.environ.get("MX_DATA_DIR")
    path = data_dir and os.path.join(data_dir, "ptb", "ptb.train.txt")
    if path and os.path.exists(path):
        words = {}
        sentences = []
        with open(path) as f:
            for line in f:
                ids = []
                for w in line.split() + ["</s>"]:
                    ids.append(words.setdefault(w, len(words) % vocab))
                sentences.append(ids)
        return sentences
    rng = np.random.RandomState(0)
    # synthetic: Markov-ish sentences so perplexity actually falls
    sentences = []
    for _ in range(600):
        n = rng.randint(5, 40)
        s = [int(rng.randint(1, vocab))]
        for _ in range(n - 1):
            s.append(int((s[-1] * 7 + rng.randint(0, 3)) % vocab))
        sentences.append(s)
    return sentences


def main():
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--buckets", type=int, nargs="+",
                    default=[10, 20, 30, 40])
    args = ap.parse_args()

    sentences = load_sentences(args.vocab)
    data_iter = mx.rnn.BucketSentenceIter(
        sentences, args.batch_size, buckets=args.buckets,
        invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=args.vocab,
                                 output_dim=args.num_embed, name="embed")
        stack = mx.rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(mx.rnn.LSTMCell(args.num_hidden,
                                      prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed,
                                  merge_outputs=True)
        pred = mx.sym.reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=args.vocab,
                                     name="pred")
        lab = mx.sym.reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(data=pred, label=lab, name="softmax")
        return net, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=data_iter.default_bucket_key,
        context=mx.tpu(0))
    model.fit(
        data_iter,
        eval_metric=mx.metric.Perplexity(ignore_label=0),
        optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.0,
                          "wd": 1e-5, "clip_gradient": 0.25},
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(
            args.batch_size, frequent=20),
    )
    data_iter.reset()
    final = model.score(data_iter,
                        mx.metric.Perplexity(ignore_label=0))
    print("final train perplexity: %.2f" % dict(final)["perplexity"])


if __name__ == "__main__":
    main()
