"""Post-training INT8 quantization (reference: example/quantization/
imagenet_gen_qsym_onednn.py — the calibrate-then-deploy flow).

Train an fp32 model (hybridized for speed), run calibration batches
through contrib.quantization.quantize_net (naive min/max or KL-entropy
thresholds — quantize_net de-hybridizes, since the int8 rewrite is
python-dispatched), then compare fp32 vs INT8 accuracy and latency on
the validation set of a synthetic learnable dataset.

    python examples/quantize_model.py [--calib-mode naive|entropy]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.contrib.quantization import quantize_net  # noqa: E402


def get_data(n=1024, batch=64, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 16, 16).astype(np.float32) * 0.1
    for i in range(n):
        c = y[i]
        x[i, 0, (c % 4) * 4:(c % 4) * 4 + 3,
          (c // 4) * 5:(c // 4) * 5 + 4] += 0.9
    split = int(n * 0.8)
    train = gluon.data.DataLoader(
        gluon.data.ArrayDataset(mx.nd.array(x[:split]),
                                mx.nd.array(y[:split].astype(np.float32))),
        batch_size=batch, shuffle=True)
    val = gluon.data.DataLoader(
        gluon.data.ArrayDataset(mx.nd.array(x[split:]),
                                mx.nd.array(y[split:].astype(np.float32))),
        batch_size=batch)
    return train, val


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    return net


def accuracy(net, data):
    metric = mx.metric.Accuracy()
    for x, y in data:
        metric.update(y, net(x))
    return metric.get()[1]


def latency(net, data, iters=3):
    xs = [x for x, _ in data]
    for x in xs[:2]:
        net(x).wait_to_read()
    t0 = time.perf_counter()
    n = 0
    outs = []
    for _ in range(iters):
        for x in xs:
            outs.append(net(x))
            n += x.shape[0]
    for o in outs:      # async dispatch: the clock must cover ALL work
        o.wait_to_read()
    return n / (time.perf_counter() - t0)


def _quantized_layers(block):
    for child in block._children.values():
        if getattr(child, "_quantized", False):
            yield child
        yield from _quantized_layers(child)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--calib-mode", choices=("naive", "entropy"),
                    default="naive")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--calib-batches", type=int, default=4)
    args = ap.parse_args()

    # everything (data arrays AND the net) on one device: the batches
    # must live where the parameters live
    with mx.Context(mx.tpu(0)):
        train, val = get_data()
        net = build_net()
        net.initialize(mx.init.Xavier())
        net.hybridize()
        loss_f = gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 2e-3})
        for epoch in range(args.epochs):
            for x, y in train:
                with autograd.record():
                    loss = loss_f(net(x), y)
                loss.backward()
                trainer.step(x.shape[0])

        fp32_acc = accuracy(net, val)
        fp32_ips = latency(net, val)

        qnet = quantize_net(net, calib_data=train,
                            num_calib_batches=args.calib_batches,
                            calib_mode=args.calib_mode)
        n_q = sum(1 for _ in _quantized_layers(qnet))
        print("quantized layers: %d" % n_q)
        int8_acc = accuracy(qnet, val)
        int8_ips = latency(qnet, val)

    print("fp32:  acc %.4f  %.0f img/s" % (fp32_acc, fp32_ips))
    print("int8:  acc %.4f  %.0f img/s  (%s calibration)"
          % (int8_acc, int8_ips, args.calib_mode))
    drop = fp32_acc - int8_acc
    print("accuracy drop: %.4f" % drop)


if __name__ == "__main__":
    main()
