"""Classic symbolic MNIST (reference:
example/image-classification/train_mnist.py).

The original v1.x workflow: compose a symbol with auto-created
parameter variables, wrap it in mx.mod.Module, and Module.fit drives
training with an NDArrayIter — no Gluon anywhere.  --network lenet
swaps the MLP for the conv net, exercising Convolution/Pooling through
the symbolic path.

    python examples/train_mnist_symbolic.py [--network mlp|lenet]
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data=data)
    net = mx.sym.FullyConnected(data=net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(data=net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(data=net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(data=net, act_type="relu", name="relu2")
    net = mx.sym.FullyConnected(data=net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def lenet_symbol():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data=data, kernel=(5, 5), num_filter=20,
                            name="conv1")
    a1 = mx.sym.Activation(data=c1, act_type="tanh")
    p1 = mx.sym.Pooling(data=a1, pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    c2 = mx.sym.Convolution(data=p1, kernel=(5, 5), num_filter=50,
                            name="conv2")
    a2 = mx.sym.Activation(data=c2, act_type="tanh")
    p2 = mx.sym.Pooling(data=a2, pool_type="max", kernel=(2, 2),
                        stride=(2, 2))
    f = mx.sym.Flatten(data=p2)
    fc1 = mx.sym.FullyConnected(data=f, num_hidden=500, name="fc1")
    a3 = mx.sym.Activation(data=fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(data=a3, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(data=fc2, name="softmax")


def get_iters(batch_size, flat):
    data_dir = os.environ.get("MX_DATA_DIR")
    if data_dir and os.path.isdir(os.path.join(data_dir, "mnist")):
        root = os.path.join(data_dir, "mnist")
        train = mx.io.MNISTIter(
            image=os.path.join(root, "train-images-idx3-ubyte"),
            label=os.path.join(root, "train-labels-idx1-ubyte"),
            batch_size=batch_size, flat=flat, shuffle=True)
        val = mx.io.MNISTIter(
            image=os.path.join(root, "t10k-images-idx3-ubyte"),
            label=os.path.join(root, "t10k-labels-idx1-ubyte"),
            batch_size=batch_size, flat=flat)
        return train, val
    # synthetic stand-in: class-dependent blobs so accuracy is learnable
    rng = np.random.RandomState(0)
    n = 2048
    y = rng.randint(0, 10, n)
    x = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
    for i in range(n):
        cls = y[i]
        x[i, 0, 2 + (cls % 5) * 5:5 + (cls % 5) * 5,
          2 + (cls // 5) * 12:8 + (cls // 5) * 12] += 0.9
    if flat:
        x = x.reshape(n, 784)
    split = int(n * 0.9)
    train = mx.io.NDArrayIter(x[:split], y[:split], batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], batch_size)
    return train, val


def main():
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(message)s")
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=("mlp", "lenet"), default="mlp")
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    flat = args.network == "mlp"
    sym = mlp_symbol() if flat else lenet_symbol()
    train, val = get_iters(args.batch_size, flat)

    model = mx.mod.Module(sym, context=mx.tpu(0))
    model.fit(
        train,
        eval_data=val,
        optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
        initializer=mx.init.Xavier(),
        eval_metric="acc",
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, 20),
    )
    acc = dict(model.score(val, mx.metric.Accuracy()))["accuracy"]
    print("final validation accuracy: %.4f" % acc)


if __name__ == "__main__":
    main()
