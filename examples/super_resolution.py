"""ESPCN super-resolution (reference:
example/gluon/super_resolution/super_resolution.py).

Sub-pixel convolution: conv stack at low resolution, then
PixelShuffle2D rearranges channels into an upscale_factor-larger image
— the FLOPs stay at LR size, which maps well onto the MXU.  After
training, the net exports through mx.onnx (the reference uses this
exact model as its canonical ONNX-export demo).

    python examples/super_resolution.py [--epochs 1] [--upscale 3]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon.contrib.nn import PixelShuffle2D  # noqa: E402


def build_net(upscale):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(64, 5, padding=2, activation="relu"),
            nn.Conv2D(64, 3, padding=1, activation="relu"),
            nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.Conv2D(upscale * upscale, 3, padding=1),
            PixelShuffle2D((upscale, upscale)))
    return net


def get_data(batch_size, upscale, n=256, hr=48):
    """(LR, HR) luminance patch pairs (synthetic; LR = mean-pooled HR,
    the standard degradation model)."""
    lr = hr // upscale
    rng = np.random.RandomState(0)
    base = rng.uniform(0, 1, (n, 1, hr, hr)).astype(np.float32)
    hr_t = mx.nd.array(base)
    # LR = mean-pooled HR (the degradation model)
    lr_t = mx.nd.Pooling(hr_t, kernel=(upscale, upscale),
                         stride=(upscale, upscale), pool_type="avg")
    assert lr_t.shape[-1] == lr
    ds = gluon.data.ArrayDataset(lr_t, hr_t)
    return gluon.data.DataLoader(ds, batch_size=batch_size, shuffle=True,
                                 last_batch="discard")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--upscale", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--export", default="")
    ap.add_argument("--max-batches", type=int,
                    default=int(os.environ.get("MX_EX_MAX_BATCHES", 0)) or
                    None)
    args = ap.parse_args()

    ctx = mx.tpu(0)
    net = build_net(args.upscale)
    with mx.Context(ctx):
        net.initialize(mx.init.Xavier())
        net.hybridize()
        l2 = gluon.loss.L2Loss()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": args.lr})

        for epoch in range(args.epochs):
            t0, seen, lsum, n_b = time.time(), 0, 0.0, 0
            for i, (lo, hi) in enumerate(
                    get_data(args.batch_size, args.upscale)):
                if args.max_batches and i >= args.max_batches:
                    break
                n_b += 1
                lo = lo.as_in_context(ctx)
                hi = hi.as_in_context(ctx)
                with autograd.record():
                    out = net(lo)
                    loss = l2(out, hi)
                loss.backward()
                trainer.step(lo.shape[0])
                lsum += float(loss.mean().asnumpy())
                seen += lo.shape[0]
            if n_b == 0:
                raise SystemExit("no batches: --batch-size exceeds the "
                                 "dataset size")
            mse = lsum / n_b * 2.0                # L2Loss halves
            print("epoch %d: mse %.5f psnr %.2f dB (%.1f patch/s)"
                  % (epoch, mse, 10 * np.log10(1.0 / max(mse, 1e-9)),
                     seen / (time.time() - t0)))

        if args.export:
            # the reference's canonical ONNX-export path: hybridized net
            # -> symbol.json + .params -> onnx protobuf
            prefix = args.export.replace(".onnx", "")
            net.export(prefix)
            from mxnet_tpu import onnx as mx_onnx
            mx_onnx.export_model(prefix + "-symbol.json",
                                 prefix + "-0000.params",
                                 [(1, 1, 16, 16)], np.float32, args.export)
            print("exported ONNX ->", args.export)


if __name__ == "__main__":
    main()
