"""DCGAN on small images (reference: example/gluon/dcgan/dcgan.py).

Shows the adversarial two-optimizer Gluon loop: a ConvTranspose
generator against a Conv discriminator, alternating updates from the
SAME autograd tape discipline the reference uses (train D on real+fake,
then train G through D's frozen weights).  Offline it runs on a
synthetic image set; point MX_DATA_DIR at an image folder for real data.

    python examples/dcgan.py [--epochs 1] [--batch-size 64] [--nz 100]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build_generator(nz, ngf=32):
    net = nn.HybridSequential()
    # 1x1 -> 4x4 -> 8x8 -> 16x16 -> 32x32
    net.add(nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False,
                               in_channels=nz),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2DTranspose(3, 4, 2, 1, use_bias=False),
            nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
            nn.LeakyReLU(0.2),
            nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
            nn.BatchNorm(), nn.LeakyReLU(0.2),
            nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False),
            nn.BatchNorm(), nn.LeakyReLU(0.2),
            nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return net


def get_data(batch_size, n=512):
    data_dir = os.environ.get("MX_DATA_DIR")
    if data_dir and os.path.isdir(os.path.join(data_dir, "images")):
        from mxnet_tpu.gluon.data.vision.datasets import ImageFolderDataset
        ds = ImageFolderDataset(os.path.join(data_dir, "images"))

        def tf(img, _label):
            img = mx.image.imresize(img, 32, 32)
            x = img.astype("float32").transpose((2, 0, 1)) / 127.5 - 1.0
            return x
        ds = ds.transform_first(lambda im: tf(im, 0))
    else:
        rng = np.random.RandomState(0)
        imgs = rng.uniform(-1, 1, (n, 3, 32, 32)).astype(np.float32)
        ds = gluon.data.ArrayDataset(mx.nd.array(imgs),
                                     mx.nd.zeros((n, 1)))
    return gluon.data.DataLoader(ds, batch_size=batch_size,
                                 shuffle=True, last_batch="discard")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--nz", type=int, default=100)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--max-batches", type=int,
                    default=int(os.environ.get("MX_EX_MAX_BATCHES", 0)) or
                    None)
    args = ap.parse_args()

    ctx = mx.tpu(0)
    netG, netD = build_generator(args.nz), build_discriminator()
    with mx.Context(ctx):
        netG.initialize(mx.init.Normal(0.02))
        netD.initialize(mx.init.Normal(0.02))
        netG.hybridize()
        netD.hybridize()

        loss_f = gluon.loss.SigmoidBinaryCrossEntropyLoss()
        trnG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
        trnD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})

        for epoch in range(args.epochs):
            t0, seen, n_b = time.time(), 0, 0
            dsum = gsum = 0.0
            for i, (real, _) in enumerate(get_data(args.batch_size)):
                if args.max_batches and i >= args.max_batches:
                    break
                n_b += 1
                bs = real.shape[0]
                real = real.as_in_context(ctx)
                noise = mx.nd.random.normal(
                    shape=(bs, args.nz, 1, 1), ctx=ctx)
                ones = mx.nd.ones((bs,), ctx=ctx)
                zeros = mx.nd.zeros((bs,), ctx=ctx)

                # D step: real -> 1, G(z) -> 0 (fake detached from G)
                with autograd.record():
                    out_r = netD(real).reshape((-1,))
                    fake = netG(noise)
                    out_f = netD(fake.detach()).reshape((-1,))
                    errD = loss_f(out_r, ones) + loss_f(out_f, zeros)
                errD.backward()
                trnD.step(bs)

                # G step: fool D (D's params get grads too but only
                # trnG.step updates G — the reference's exact recipe)
                with autograd.record():
                    out = netD(fake).reshape((-1,))
                    errG = loss_f(out, ones)
                errG.backward()
                trnG.step(bs)

                dsum += float(errD.mean().asnumpy())
                gsum += float(errG.mean().asnumpy())
                seen += bs
            if n_b == 0:
                raise SystemExit("no batches: --batch-size exceeds the "
                                 "dataset size")
            print("epoch %d: lossD %.4f lossG %.4f (%.1f img/s)"
                  % (epoch, dsum / n_b, gsum / n_b,
                     seen / (time.time() - t0)))


if __name__ == "__main__":
    main()
