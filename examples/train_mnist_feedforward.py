"""The original pre-Module v1.x workflow, verbatim (reference:
example/image-classification/train_mnist.py at the FeedForward era /
python/mxnet/model.py class FeedForward): build a symbol, hand it to
mx.model.FeedForward with optimizer hyper-parameters as kwargs, call
fit/predict/score, save a prefix-epoch checkpoint and load it back.

    python examples/train_mnist_feedforward.py [--epochs N]
"""
import argparse
import logging
import os
import sys
import tempfile
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def mlp_symbol():
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data=data)
    net = mx.sym.FullyConnected(data=net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(data=net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(data=net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def synthetic_mnist(n=2048, seed=0):
    """Offline stand-in with MNIST geometry: each digit class is a fixed
    28x28 prototype plus noise, so the fit generalizes to held-out data
    the way real MNIST does."""
    protos = np.random.RandomState(1234).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    Y = rng.randint(0, 10, n)
    X = (protos[Y] + 2.0 * rng.randn(n, 784)).astype(np.float32)
    return X.reshape(n, 1, 28, 28), Y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    X, Y = synthetic_mnist()
    Xval, Yval = synthetic_mnist(512, seed=1)
    val_iter = mx.io.NDArrayIter(Xval, Yval, batch_size=128,
                                 label_name="softmax_label")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = mx.model.FeedForward(
            symbol=mlp_symbol(), num_epoch=args.epochs,
            learning_rate=args.lr, momentum=0.9, numpy_batch_size=128,
            initializer=mx.init.Xavier())
    model.fit(X=X, y=Y, eval_data=(Xval, Yval),
              batch_end_callback=mx.callback.Speedometer(128, 8))

    acc = model.score(val_iter)
    print("final test accuracy %.4f" % acc)
    assert acc > 0.6, acc

    prefix = os.path.join(tempfile.mkdtemp(), "mnist-ff")
    model.save(prefix)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        loaded = mx.model.FeedForward.load(prefix, args.epochs)
    preds = loaded.predict(Xval)
    agree = float((preds.argmax(1) == model.predict(Xval).argmax(1)).mean())
    assert agree == 1.0, agree
    print("checkpoint roundtrip OK (%s-%04d.params)" % (prefix, args.epochs))


if __name__ == "__main__":
    main()
