"""Async parameter-server training (reference: the dist_async mode of
example/image-classification/common/fit.py + tools/launch.py -s).

Each worker streams its own batches; the PS applies every push the
moment it arrives (server-side SGD), so fast workers never wait for slow
ones — the stale-tolerant tradeoff sync collectives cannot express.

Run (1 server + 2 workers on this host):

    python tools/launch.py -n 2 -s 1 --launcher local -- \\
        python examples/train_dist_async.py [--steps 50]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, kvstore, nd, optimizer  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    kv = kvstore.create("dist_async")
    rank, nworkers = kv.rank, kv.num_workers
    mx.random.seed(rank)                      # workers see different data

    # tiny regression net; weights live on the PS
    net = gluon.nn.Dense(1, in_units=8)
    net.initialize(mx.init.Xavier())
    params = list(net.collect_params().values())
    for i, param in enumerate(params):
        kv.init(i, param.data())
    kv.set_optimizer(optimizer.SGD(learning_rate=args.lr))
    for i, param in enumerate(params):        # start from server state
        kv.pull(i, out=param.data())

    rng = np.random.RandomState(100 + rank)
    w_true = np.arange(8, dtype=np.float32).reshape(8, 1) / 8.0
    for step in range(args.steps):
        X = nd.array(rng.randn(args.batch_size, 8).astype(np.float32))
        y = nd.array(X.asnumpy() @ w_true)
        with autograd.record():
            loss = ((net(X) - y) ** 2).mean()
        loss.backward()
        for i, param in enumerate(params):
            kv.push(i, param.grad())          # applied server-side NOW
            kv.pull(i, out=param.data())      # whatever is current
        if step % 10 == 0:
            print("rank %d step %d loss %.4f" % (rank, step,
                                                 float(loss.asnumpy())))
    kv._barrier()
    final = float(loss.asnumpy())
    print("rank %d FINAL loss %.4f (workers=%d)" % (rank, final, nworkers))


if __name__ == "__main__":
    main()
