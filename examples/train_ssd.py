"""SSD detection training (BASELINE config 4; reference:
example/ssd/train.py).  Real data: point --rec at an ImageDetIter .rec
pack (tools/im2rec.py --pack-label); offline it builds a synthetic
one-box dataset so the script runs anywhere.

    python examples/train_ssd.py [--epochs 2]
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# a wedged accelerator tunnel HANGS jax backend init — probe with a
# timeout and fall back to CPU (the repo-wide entry-point pattern)
from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, recordio
from mxnet_tpu.gluon.model_zoo.ssd import SSDMultiBoxLoss, ssd_toy
from mxnet_tpu.image.detection import ImageDetIter
from mxnet_tpu.metric import VOC07MApMetric


def synthetic_rec(n=64, edge=64):
    rng = np.random.RandomState(0)
    d = tempfile.mkdtemp(prefix="ssd_rec_")
    prefix = os.path.join(d, "det")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = np.full((edge, edge, 3), 30, np.uint8)
        bw = rng.randint(edge // 4, edge // 2)
        x0 = rng.randint(0, edge - bw)
        y0 = rng.randint(0, edge - bw)
        img[y0:y0 + bw, x0:x0 + bw] = 220
        label = np.concatenate(
            [[2, 5, 0], [x0 / edge, y0 / edge, (x0 + bw) / edge,
                         (y0 + bw) / edge]]).astype(np.float32)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, quality=95))
    w.close()
    return prefix + ".rec"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", default=None, help=".rec with det labels")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--data-shape", type=int, default=64)
    args = ap.parse_args()

    rec = args.rec or synthetic_rec()
    it = ImageDetIter(path_imgrec=rec,
                      data_shape=(3, args.data_shape, args.data_shape),
                      batch_size=args.batch_size, shuffle=True,
                      rand_mirror=True)

    mx.random.seed(0)
    net = ssd_toy(classes=1)
    net.initialize(mx.init.Xavier(), ctx=mx.tpu(0))
    loss_fn = SSDMultiBoxLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9})
    for epoch in range(args.epochs):
        it.reset()
        losses = []
        for batch in it:
            x = batch.data[0].as_in_context(mx.tpu(0)) / 255.0
            y = batch.label[0].as_in_context(mx.tpu(0))
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                loc_t, loc_m, cls_t = net.targets(anchors, cls_preds, y)
                loss = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
            loss.backward()
            trainer.step(x.shape[0])
            losses.append(float(loss.asnumpy().item()))
        print("epoch %d loss %.4f" % (epoch, sum(losses) / len(losses)))

    metric = VOC07MApMetric()
    it.reset()
    for batch in it:
        anchors, cls_preds, box_preds = net(
            batch.data[0].as_in_context(mx.tpu(0)) / 255.0)
        dets = net.detect(anchors, cls_preds, box_preds)
        n = batch.data[0].shape[0] - batch.pad   # drop wrap-around padding
        metric.update([batch.label[0][:n]], [dets[:n]])
    print("train-set %s=%.4f" % metric.get())


if __name__ == "__main__":
    main()
