"""Gluon MNIST MLP (BASELINE config 0; reference:
example/gluon/mnist/mnist.py).

Runs on the real dataset when MX_DATA_DIR points at MNIST idx files,
otherwise on the synthetic stand-in so the script is runnable offline:

    python examples/train_mnist_gluon.py [--epochs 2] [--hybridize]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# a wedged accelerator tunnel HANGS jax backend init — probe with a
# timeout and fall back to CPU (the repo-wide entry-point pattern)
from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def get_data(batch_size):
    data_dir = os.environ.get("MX_DATA_DIR")
    from mxnet_tpu.gluon.data.vision import transforms as T
    to_tensor = T.ToTensor()
    if data_dir:
        from mxnet_tpu.gluon.data.vision import MNIST
        root = os.path.join(data_dir, "mnist")
        train = MNIST(root=root, train=True).transform_first(to_tensor)
        test = MNIST(root=root, train=False).transform_first(to_tensor)
    else:
        from mxnet_tpu.gluon.data.vision import SyntheticImageDataset
        train = SyntheticImageDataset(num_samples=2048, shape=(28, 28, 1),
                                      num_classes=10).transform_first(
                                          to_tensor)
        test = SyntheticImageDataset(num_samples=512, shape=(28, 28, 1),
                                     num_classes=10).transform_first(
                                         to_tensor)
    return (gluon.data.DataLoader(train, batch_size, shuffle=True),
            gluon.data.DataLoader(test, batch_size))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--hybridize", action="store_true")
    args = ap.parse_args()

    ctx = mx.tpu(0)
    train_loader, test_loader = get_data(args.batch_size)

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize()

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        metric.reset()
        for x, y in train_loader:
            x, y = x.as_in_context(ctx), y.as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        name, acc = metric.get()
        print("epoch %d train %s=%.4f" % (epoch, name, acc))
    metric.reset()
    for x, y in test_loader:
        metric.update([y.as_in_context(ctx)],
                      [net(x.as_in_context(ctx))])
    print("final test %s=%.4f" % metric.get())


if __name__ == "__main__":
    main()
