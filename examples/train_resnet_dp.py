"""Data-parallel ResNet training over a device mesh (BASELINE config 1
path; reference: example/image-classification/train_imagenet.py with
kvstore, rebuilt on the whole-step-jitted parallel.TrainStep).

Single host: uses every visible chip via a 1-axis dp mesh. Multi-host:
launch with tools/launch.py -n <N> and each worker feeds its batch shard.

    python examples/train_resnet_dp.py [--model resnet18_v1] [--steps 10]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# a wedged accelerator tunnel HANGS jax backend init — probe with a
# timeout and fall back to CPU (the repo-wide entry-point pattern)
from mxnet_tpu.base import ensure_live_backend  # noqa: E402

ensure_live_backend()

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--batch-size", type=int, default=32,
                    help="GLOBAL batch size")
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--classes", type=int, default=100)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import init_process_group, make_mesh, TrainStep

    if os.environ.get("MX_NUM_PROCESSES"):
        init_process_group()

    mx.random.seed(0)
    with mx.Context("cpu"):
        net = getattr(vision, args.model)(classes=args.classes)
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((1, 3, args.image_size, args.image_size)))

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        onehot = jax.nn.one_hot(labels, args.classes, dtype=logp.dtype)
        return -jnp.mean(jnp.sum(logp * onehot, axis=-1))

    mesh = make_mesh(axes=("dp",), devices=jax.devices())
    step = TrainStep(net, loss_fn, mesh, learning_rate=args.lr,
                     momentum=0.9)

    nproc = jax.process_count()
    local_bs = args.batch_size // nproc
    rng = np.random.RandomState(jax.process_index())
    for i in range(args.steps):
        x = rng.randn(local_bs, 3, args.image_size,
                      args.image_size).astype(np.float32)
        y = rng.randint(0, args.classes, local_bs).astype(np.int32)
        loss = step(x, y)
        if jax.process_index() == 0:
            val = float(np.asarray(jax.device_get(
                loss._jax if hasattr(loss, "_jax") else loss)))
            print("step %d loss %.4f" % (i, val))
    step.write_back(net)
    if jax.process_index() == 0:
        net.export("resnet_dp_trained")
        print("exported resnet_dp_trained-symbol.json / -0000.params")


if __name__ == "__main__":
    main()
